"""Dense slot-aligned store + N-replica fan-in lattice join.

This is the TPU-native realization of the reference's replica merge
protocol (C9) at scale: instead of N replicas converging by N-1
sequential pairwise ``merge`` calls (crdt.dart:77-94, each O(n_remote)
with hash lookups), a *dense* changeset batch ``[R, N]`` — R replicas ×
N key slots — fans into the local store in one fused reduction:

1. **Replica reduce**: per key slot, the winning remote record is the
   lexicographic ``(lt, node)`` maximum over the R axis, with the
   LOWEST replica index winning exact ties — exactly what sequential
   pairwise merging produces (the first replica to merge a record wins;
   later identical records lose the local-wins-on-tie compare,
   crdt.dart:84).
2. **LWW vs local** (crdt.dart:83-84): strict ``(lt, node)`` compare so
   local wins exact ties.
3. **Clock absorption + guards** (crdt.dart:82, hlc.dart:80-97): the
   per-record ``Hlc.recv`` fold collapses to one max-reduction; the
   duplicate-node / drift guard masks are computed against the running
   canonical clock (exclusive cummax over the records in r-major
   order — the order a single sequential merge of the concatenated
   changesets would visit them), because recv's fast path skips the
   checks whenever the canonical clock is already ahead (hlc.dart:85).
4. **Re-stamp** (crdt.dart:86-87): winners keep the remote event hlc;
   ``modified`` lanes get the final canonical time.

Semantics note: on the *store lanes and canonical clock*,
``fanin_step`` ≡ ONE ``Crdt.merge`` of the conflict-resolved union of
the R changesets (ties to the lowest r) — differentially tested against
the scalar oracle in exactly that formulation. The *guard masks* are
stricter than a union merge: they visit EVERY record in r-major order
(like sequential merging, where recv runs for winners and losers alike,
crdt.dart:82), so a duplicate-node/drift record that would lose its
per-key conflict still trips — the conservative choice for a safety
check. Sequential pairwise merging additionally bumps the clock to wall
time between rounds (crdt.dart:93), which can shield later rounds'
records from the slow path; the fan-in evaluates all records against
one pre-bump running clock.

Values ride in an int64 ``val`` lane — either the scalar payload itself
or an index into a host-side payload table (SURVEY.md §7 hard part 4:
variable-length values never enter the reduction).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .merge import recv_guards
from ..obs import device as _obs_device

_NEG = -(2 ** 62)
_I32_NEG = -(2 ** 31)

# Dispatch-ledger registration (docs/OBSERVABILITY.md device plane):
# every host wrapper below reports its device dispatches; declaring the
# names at import time is what the crdtlint
# `dispatch-ledger-unregistered` gate verifies.
_obs_device.register(
    "dense.fanin_step", "dense.fanin_stream", "dense.sparse_fanin_step",
    "dense.wire_join_step", "dense.merge_repack_step",
    "dense.delta_mask", "dense.range_delta_mask",
    "dense.max_logical_time", "dense.put_scatter",
    "dense.record_scatter", "dense.delete_scatter",
    "dense.ingest_scatter", "dense.gc_purge", "dense.compact_remap")


class DenseStore(NamedTuple):
    """Key-slot-aligned columnar record store: slot i holds key i.

    The dense layout drops the host-side key<->slot dict of
    `ops.merge.Store` entirely — the natural fit for integer key spaces
    and for key-space sharding across a device mesh (`crdt_tpu.parallel`).
    """
    lt: jax.Array        # int64[N] record hlc logicalTime (0 = never set)
    node: jax.Array      # int32[N] record hlc node ordinal
    val: jax.Array       # int64[N] payload (scalar or host-table index)
    mod_lt: jax.Array    # int64[N] modified logicalTime (local-only lane)
    mod_node: jax.Array  # int32[N] modified node ordinal
    occupied: jax.Array  # bool[N]
    tomb: jax.Array      # bool[N] value is None (record.dart:17)

    @property
    def n_slots(self) -> int:
        return self.lt.shape[0]


class DenseChangeset(NamedTuple):
    """R replica changesets over the same N key slots, padded with
    ``valid=False``. Lane [r, k] is replica r's record for key k."""
    lt: jax.Array     # int64[R, N]
    node: jax.Array   # int32[R, N]
    val: jax.Array    # int64[R, N]
    tomb: jax.Array   # bool[R, N]
    valid: jax.Array  # bool[R, N]


class FaninResult(NamedTuple):
    new_canonical: jax.Array   # int64 scalar (pre final-send-bump)
    win_count: jax.Array       # int32 number of adopted records
    win: jax.Array             # bool[N] per-slot adopted mask (watch/C13)
    any_bad: jax.Array         # bool — some recv guard tripped
    first_bad: jax.Array       # flat r-major index of first offender
    #                            (int32 one-shot; int64 from streams)
    first_is_dup: jax.Array    # bool — duplicate-node (vs drift) there
    canonical_at_fail: jax.Array  # int64 canonical BEFORE failing record


def empty_dense_store(n_slots: int) -> DenseStore:
    return DenseStore(
        lt=jnp.zeros((n_slots,), jnp.int64),
        node=jnp.zeros((n_slots,), jnp.int32),
        val=jnp.zeros((n_slots,), jnp.int64),
        mod_lt=jnp.zeros((n_slots,), jnp.int64),
        mod_node=jnp.zeros((n_slots,), jnp.int32),
        occupied=jnp.zeros((n_slots,), bool),
        tomb=jnp.zeros((n_slots,), bool),
    )


def lex_fold(cs: DenseChangeset, lt: jax.Array, node: jax.Array,
             val: jax.Array, tomb: jax.Array
             ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array,
                        jax.Array]:
    """Fold the replica rows into per-key running-best lanes via the
    strict lexicographic (lt, node) compare.

    Seeded with ``(lt, node, val, tomb)`` — the local store lanes (so
    the LWW join and the replica reduce fuse into one pass; local keeps
    exact ties because the compare is strict, crdt.dart:84) or ``_NEG``
    sentinels (pure reduce). Ties between replica rows go to the LOWEST
    index — sequential-merge parity (see module docstring). The row
    loop is Python-unrolled over the static R dimension: each row is
    one fused elementwise compare+select, no argmax/gather — the shape
    XLA tiles well on TPU, where int64 lanes are emulated and gather is
    expensive.

    Returns ``(lt, node, val, tomb, from_row)`` where ``from_row``
    marks keys whose running best came from a replica row."""
    from_row = jnp.zeros(lt.shape, bool)
    for r in range(cs.lt.shape[0]):
        lt_r = jnp.where(cs.valid[r], cs.lt[r], _NEG)
        # Mask node as well: at sentinel lt an invalid row must not win
        # the node tie-break against the sentinel seed.
        node_r = jnp.where(cs.valid[r], cs.node[r], _I32_NEG)
        better = (lt_r > lt) | ((lt_r == lt) & (node_r > node))
        lt = jnp.where(better, lt_r, lt)
        node = jnp.where(better, cs.node[r], node)
        val = jnp.where(better, cs.val[r], val)
        tomb = jnp.where(better, cs.tomb[r], tomb)
        from_row = from_row | better
    return lt, node, val, tomb, from_row


def reduce_replicas(cs: DenseChangeset) -> Tuple[jax.Array, jax.Array,
                                                 jax.Array, jax.Array,
                                                 jax.Array]:
    """Stable lexicographic (lt, node) max over the replica axis.

    Returns per-key ``(best_lt, best_node, best_val, best_tomb,
    any_valid)``; ties on (lt, node) go to the LOWEST replica index
    (sequential-merge parity — see module docstring). Keys with no
    valid record report ``best_lt == _NEG``/``best_node == _I32_NEG``."""
    n = cs.lt.shape[1]
    lt, node, val, tomb, any_valid = lex_fold(
        cs,
        jnp.full((n,), _NEG, cs.lt.dtype),
        jnp.full((n,), _I32_NEG, cs.node.dtype),
        jnp.zeros((n,), cs.val.dtype),
        jnp.zeros((n,), bool),
    )
    return lt, node, val, tomb, any_valid


@jax.jit
def _fanin_step_jit(store: DenseStore, cs: DenseChangeset,
                    canonical_lt: jax.Array, local_node: jax.Array,
                    wall_millis: jax.Array,
                    stamp_lt: Optional[jax.Array] = None
                    ) -> Tuple[DenseStore, FaninResult]:
    any_bad, first_bad, first_is_dup, canonical_at_fail = recv_guards(
        cs.lt, cs.node, cs.valid, canonical_lt, local_node, wall_millis)

    new_canonical = jnp.maximum(
        canonical_lt, jnp.max(jnp.where(cs.valid, cs.lt, _NEG)))
    stamp = new_canonical if stamp_lt is None else stamp_lt

    # Replica reduce + LWW join in ONE fused fold: seed the running best
    # with the local store lanes (empty slots as _NEG sentinels so any
    # valid remote beats them; occupied slots win exact ties because the
    # fold's compare is strict, crdt.dart:84).
    lt, node, val, tomb, win = lex_fold(
        cs,
        jnp.where(store.occupied, store.lt, _NEG),
        store.node, store.val, store.tomb)

    new_store = DenseStore(
        lt=jnp.where(win, lt, store.lt),
        node=jnp.where(win, node, store.node),
        val=val,
        mod_lt=jnp.where(win, stamp, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | win,
        tomb=tomb,
    )
    return new_store, FaninResult(
        new_canonical=new_canonical,
        win_count=jnp.sum(win).astype(jnp.int32),
        win=win,
        any_bad=any_bad,
        first_bad=first_bad,
        first_is_dup=first_is_dup,
        canonical_at_fail=canonical_at_fail,
    )


def fanin_step(store: DenseStore, cs: DenseChangeset,
               canonical_lt: jax.Array, local_node: jax.Array,
               wall_millis: jax.Array,
               stamp_lt: Optional[jax.Array] = None
               ) -> Tuple[DenseStore, FaninResult]:
    """One fused R-replica fan-in lattice join. See module docstring.

    ``stamp_lt`` overrides the ``modified`` stamp for winners (default:
    this step's post-absorption canonical). Streaming executors pass the
    whole stream's final canonical so chunked execution stays
    bit-identical to the one-shot join (crdt.dart:86-87 stamps winners
    with the canonical AFTER all records were absorbed)."""
    with _obs_device.record("dense.fanin_step", dim=cs.lt.shape[0]):
        return _fanin_step_jit(store, cs, canonical_lt, local_node,
                               wall_millis, stamp_lt)


@jax.jit
def _fanin_stream_jit(store: DenseStore, chunks: DenseChangeset,
                      canonical_lt: jax.Array, local_node: jax.Array,
                      wall_millis: jax.Array,
                      stamp_lt: Optional[jax.Array] = None
                      ) -> Tuple[DenseStore, FaninResult]:
    """Streaming fan-in over [C, Rc, N] chunked changesets via lax.scan.

    Replica counts too large for one resident [R, N] batch stream
    through in chunks; the store is the scan carry. With the default
    ``stamp_lt=None`` this is equivalent to C sequential ``fanin_step``
    merges (each chunk's winners stamped with that chunk's
    post-absorption canonical — the ``modified`` semantics sequential
    pairwise merging produces, crdt.dart:87). Passing the stream-final
    canonical as ``stamp_lt`` instead makes the result bit-identical to
    ONE fused join of all C×Rc rows (union semantics — what
    ``DenseCrdt.merge_many`` promises regardless of executor)."""

    chunk_size = chunks.lt.shape[1] * chunks.lt.shape[2]

    def step(carry, chunk):
        st, canon, offset, bad, fb, fd, caf, wins, winm = carry
        st2, res = _fanin_step_jit(st, chunk, canon, local_node,
                                   wall_millis, stamp_lt)
        # Keep the FIRST failure's diagnostics across chunks; first_bad
        # is reported as a GLOBAL flat r-major index across the whole
        # stream — int64: C*Rc*N exceeds int32 at exactly the scales
        # this streaming path exists for.
        keep_old = bad
        return (st2, res.new_canonical, offset + chunk_size,
                bad | res.any_bad,
                jnp.where(keep_old, fb,
                          offset + res.first_bad.astype(jnp.int64)),
                jnp.where(keep_old, fd, res.first_is_dup),
                jnp.where(keep_old, caf, res.canonical_at_fail),
                wins + res.win_count, winm | res.win), None

    init = (store, canonical_lt, jnp.int64(0),
            jnp.asarray(False), jnp.int64(0), jnp.asarray(False),
            jnp.int64(0), jnp.int32(0),
            jnp.zeros((store.n_slots,), bool))
    (st, canon, _, bad, fb, fd, caf, wins, winm), _ = jax.lax.scan(
        step, init, chunks)
    # Adopted-record accounting follows the stamping semantics: the
    # sequential mode (stamp_lt=None) counts a slot once per chunk that
    # re-won it, like C sequential merges would; the union mode counts
    # winning SLOTS from the final mask, like the one-shot join.
    win_count = (wins if stamp_lt is None
                 else jnp.sum(winm).astype(jnp.int32))
    return st, FaninResult(new_canonical=canon, win_count=win_count,
                           win=winm, any_bad=bad, first_bad=fb,
                           first_is_dup=fd, canonical_at_fail=caf)


def fanin_stream(store: DenseStore, chunks: DenseChangeset,
                 canonical_lt: jax.Array, local_node: jax.Array,
                 wall_millis: jax.Array,
                 stamp_lt: Optional[jax.Array] = None
                 ) -> Tuple[DenseStore, FaninResult]:
    """See `_fanin_stream_jit` — this host wrapper only adds the
    dispatch-ledger record (one dispatch per whole stream; the chunks
    scan inside it is a single program)."""
    with _obs_device.record("dense.fanin_stream",
                            dim=chunks.lt.shape[0] * chunks.lt.shape[1]):
        return _fanin_stream_jit(store, chunks, canonical_lt,
                                 local_node, wall_millis, stamp_lt)


def _sparse_fanin_body(store: DenseStore, slot: jax.Array,
                       lt: jax.Array, node: jax.Array, val: jax.Array,
                       tomb: jax.Array, valid: jax.Array,
                       stamp_lt: jax.Array, local_node: jax.Array
                       ) -> Tuple[DenseStore, jax.Array]:
    l_lt = store.lt.at[slot].get(mode="fill", fill_value=0)
    l_node = store.node.at[slot].get(mode="fill", fill_value=0)
    l_occ = store.occupied.at[slot].get(mode="fill", fill_value=False)

    # Strict (lt, node) compare: local wins exact ties (crdt.dart:84).
    remote_newer = (lt > l_lt) | ((lt == l_lt) & (node > l_node))
    win = valid & (~l_occ | remote_newer)

    target = jnp.where(win, slot, store.n_slots).astype(jnp.int32)
    k = slot.shape[0]
    new_store = DenseStore(
        lt=store.lt.at[target].set(lt, mode="drop"),
        node=store.node.at[target].set(node, mode="drop"),
        val=store.val.at[target].set(val, mode="drop"),
        mod_lt=store.mod_lt.at[target].set(
            jnp.zeros((k,), jnp.int64) + stamp_lt, mode="drop"),
        mod_node=store.mod_node.at[target].set(
            jnp.zeros((k,), jnp.int32) + local_node, mode="drop"),
        occupied=store.occupied.at[target].set(True, mode="drop"),
        tomb=store.tomb.at[target].set(tomb, mode="drop"),
    )
    return new_store, win


def _wire_join_body(store: DenseStore, lt: jax.Array, node: jax.Array,
                    val: jax.Array, tomb: jax.Array, valid: jax.Array,
                    stamp_lt: jax.Array, local_node: jax.Array
                    ) -> Tuple[DenseStore, jax.Array]:
    lt = jnp.where(valid, lt, _NEG)
    node = node.astype(jnp.int32)
    val = val.astype(jnp.int64)
    # Strict (lt, node) compare: local wins exact ties (crdt.dart:84).
    remote_newer = ((lt > store.lt) |
                    ((lt == store.lt) & (node > store.node)))
    win = valid & (~store.occupied | remote_newer)
    new_store = DenseStore(
        lt=jnp.where(win, lt, store.lt),
        node=jnp.where(win, node, store.node),
        val=jnp.where(win, val, store.val),
        mod_lt=jnp.where(win, stamp_lt, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | win,
        tomb=jnp.where(win, tomb, store.tomb),
    )
    return new_store, win


# Jit-cached merge entry points, keyed on (donate, sharding) like the
# local-write scatters below: donating the old store lets XLA update
# the O(n_slots) lanes in place for an O(k) delta (on backends that
# honor donation), and the sharding constraint pins a sharded model's
# merge output onto its key-axis layout — without it XLA picks, and
# every sharded merge pays a full-store re-shard copy on top of the
# multi-chip dispatch floor (docs/PERF.md MULTICHIP_SCALE_r05).

import functools as _ft


@_ft.lru_cache(maxsize=None)
def _sparse_fanin_jit(donate: bool, sharding=None):
    def step(store, slot, lt, node, val, tomb, valid, stamp_lt,
             local_node):
        new_store, win = _sparse_fanin_body(
            store, slot, lt, node, val, tomb, valid, stamp_lt,
            local_node)
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        return new_store, win
    return jax.jit(step, donate_argnums=(0,) if donate else ())


@_ft.lru_cache(maxsize=None)
def _wire_join_jit(donate: bool, sharding=None):
    def step(store, lt, node, val, tomb, valid, stamp_lt, local_node):
        new_store, win = _wire_join_body(store, lt, node, val, tomb,
                                         valid, stamp_lt, local_node)
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        return new_store, win
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def sparse_fanin_step(store: DenseStore, slot: jax.Array, lt: jax.Array,
                      node: jax.Array, val: jax.Array, tomb: jax.Array,
                      valid: jax.Array, stamp_lt: jax.Array,
                      local_node: jax.Array, *, donate: bool = False,
                      sharding=None) -> Tuple[DenseStore, jax.Array]:
    """O(k) slot-indexed scatter join of a k-record delta into an
    N-slot store — the wire-delta shape (a 10-record JSON sync into a
    1M-slot replica must not materialize 1M-wide lanes).

    Clock absorption and recv guards are the CALLER's job (run
    host-side in the payload's visit order, crdt.dart:80-85, before
    invoking); ``stamp_lt`` is the post-absorption canonical that
    winners' ``modified`` lanes take (crdt.dart:86-87). Slots must be
    unique (a dict-keyed delta guarantees it). ``donate`` hands the old
    store buffers to XLA (caller must not reuse them); ``sharding``
    pins the output layout. Returns ``(new_store, win)`` with ``win``
    over the k entries."""
    with _obs_device.record("dense.sparse_fanin_step",
                            dim=slot.shape[0],
                            donated=store.lt if donate else None):
        return _sparse_fanin_jit(donate, sharding)(
            store, slot, lt, node, val, tomb, valid, stamp_lt,
            local_node)


def wire_join_step(store: DenseStore, lt: jax.Array, node: jax.Array,
                   val: jax.Array, tomb: jax.Array, valid: jax.Array,
                   stamp_lt: jax.Array, local_node: jax.Array, *,
                   donate: bool = False, sharding=None
                   ) -> Tuple[DenseStore, jax.Array]:
    """Elementwise N-wide join of a SLOT-ALIGNED wire delta (lane i is
    slot i's record, ``valid`` masking absent slots) — the large-k
    companion of `sparse_fanin_step`: no gather, no scatter (TPU
    scatters serialize per index; at k ≈ n_slots the elementwise form
    is >10× faster), just one fused compare/select sweep.

    Clock absorption and recv guards are the CALLER's job (the host
    recv fold, crdt.dart:80-85); ``stamp_lt`` is the post-absorption
    canonical for winners' ``modified`` lanes (crdt.dart:86-87).
    ``node`` may arrive int16 and ``val`` int32 (narrow wire
    transfers); both widen in-jit, so the host→device bytes shrink
    without touching the compare semantics. ``donate``/``sharding``
    follow `sparse_fanin_step`. Returns ``(new_store, win)`` with
    ``win`` over the N slots."""
    with _obs_device.record("dense.wire_join_step", dim=lt.shape[0],
                            donated=store.lt if donate else None):
        return _wire_join_jit(donate, sharding)(
            store, lt, node, val, tomb, valid, stamp_lt, local_node)


@_ft.lru_cache(maxsize=None)
def _merge_repack_jit(donate: bool, sharding=None):
    def step(store, slot, lt, node, val, tomb, valid, stamp_lt,
             local_node, since_lt):
        new_store, win = _sparse_fanin_body(
            store, slot, lt, node, val, tomb, valid, stamp_lt,
            local_node)
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        mask = new_store.occupied & (new_store.mod_lt >= since_lt)
        return new_store, win, mask
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def merge_repack_step(store: DenseStore, slot: jax.Array, lt: jax.Array,
                      node: jax.Array, val: jax.Array, tomb: jax.Array,
                      valid: jax.Array, stamp_lt: jax.Array,
                      local_node: jax.Array, since_lt: jax.Array, *,
                      donate: bool = False, sharding=None
                      ) -> Tuple[DenseStore, jax.Array, jax.Array]:
    """`sparse_fanin_step` fused with the NEXT pack's delta mask — the
    gossip relay op: merging a peer's delta and computing
    ``occupied & (mod_lt >= since_lt)`` over the post-merge store in
    ONE program replaces the two dispatches (merge, then
    `dense_delta_mask` on the following `pack_since` miss) a relay
    round otherwise pays. Same caller contract as `sparse_fanin_step`;
    ``since_lt`` is the watermark the next outbound pack will be
    bounded by (inclusive, map_crdt.dart:44-45). Returns
    ``(new_store, win, mask)`` with ``mask`` over the N slots."""
    with _obs_device.record("dense.merge_repack_step",
                            dim=slot.shape[0],
                            donated=store.lt if donate else None):
        return _merge_repack_jit(donate, sharding)(
            store, slot, lt, node, val, tomb, valid, stamp_lt,
            local_node, since_lt)


@jax.jit
def _delta_mask_jit(store: DenseStore, since_lt: jax.Array) -> jax.Array:
    return store.occupied & (store.mod_lt >= since_lt)


def dense_delta_mask(store: DenseStore, since_lt: jax.Array) -> jax.Array:
    """modifiedSince filter — INCLUSIVE bound on the modified lane
    (map_crdt.dart:44-45)."""
    with _obs_device.record("dense.delta_mask", dim=store.lt.shape[0]):
        return _delta_mask_jit(store, since_lt)


@_ft.lru_cache(maxsize=None)
def _range_mask_jit():
    def step(store: DenseStore, since_lt: jax.Array, los: jax.Array,
             his: jax.Array) -> jax.Array:
        base = store.occupied & (store.mod_lt >= since_lt)
        idx = jnp.arange(store.lt.shape[0], dtype=jnp.int64)
        in_range = jnp.any((idx[None, :] >= los[:, None])
                           & (idx[None, :] < his[:, None]), axis=0)
        return base & in_range

    return jax.jit(step)


def dense_range_delta_mask(store: DenseStore, since_lt: jax.Array,
                           los: jax.Array, his: jax.Array) -> jax.Array:
    """`dense_delta_mask` restricted to a union of half-open slot
    spans ``[los[i], his[i])`` — the anti-entropy range pack
    (docs/ANTIENTROPY.md): after a Merkle walk localizes divergence to
    a few leaf ranges, only those slots feed the pack. Callers pad the
    span arrays to a power-of-two length with empty ``lo == hi == 0``
    spans so the jit cache sees O(log) distinct shapes. Pass
    ``since_lt = 0`` for a clock-unbounded range scan (every occupied
    slot has ``mod_lt > 0``, so 0 never filters)."""
    with _obs_device.record("dense.range_delta_mask",
                            dim=los.shape[0]):
        return _range_mask_jit()(store, since_lt, los, his)


@jax.jit
def _max_logical_time_jit(store: DenseStore) -> jax.Array:
    return jnp.max(jnp.where(store.occupied, store.lt, 0))


def dense_max_logical_time(store: DenseStore) -> jax.Array:
    """refreshCanonicalTime's reduction (crdt.dart:114-121)."""
    with _obs_device.record("dense.max_logical_time",
                            dim=store.lt.shape[0]):
        return _max_logical_time_jit(store)


def pad_replica_rows(cs: DenseChangeset, multiple: int) -> DenseChangeset:
    """Pad the replica axis with ``valid=False`` rows (all-zero lanes)
    up to a multiple — shared by the streamed and sharded executors so
    padding semantics can't diverge."""
    pad = (-cs.lt.shape[0]) % multiple
    if not pad:
        return cs
    return DenseChangeset(*(
        jnp.concatenate([lane, jnp.zeros((pad,) + lane.shape[1:],
                                         lane.dtype)])
        for lane in cs))


def store_to_changeset(store: DenseStore,
                       since_lt: Optional[jax.Array] = None
                       ) -> DenseChangeset:
    """Export a store as a 1-replica changeset (the outbound half of the
    anti-entropy round, crdt.dart:124-135): full state, or the delta of
    records with ``modified >= since_lt``."""
    valid = (store.occupied if since_lt is None
             else dense_delta_mask(store, since_lt))
    return DenseChangeset(lt=store.lt[None], node=store.node[None],
                          val=store.val[None], tomb=store.tomb[None],
                          valid=valid[None])


# --- local-write scatters (putAll/delete, crdt.dart:46-58) ---
#
# One fused jit per batch shape instead of seven eager `.at[].set`
# dispatches, with store-buffer donation where the backend supports it
# (TPU; CPU ignores donation with a warning, so the caller picks) —
# a local write into an n-slot store must not copy n-wide lanes.

import functools as _functools


# ``sharding``: optional NamedSharding pinned onto the OUTPUT store
# inside the jit (with_sharding_constraint) — a sharded model's local
# write then lands already laid out, instead of XLA choosing and the
# model paying a full-store re-shard copy afterwards.
@_functools.lru_cache(maxsize=None)
def _put_scatter(donate: bool, sharding=None):
    def step(store: DenseStore, slots, values, tombs, t, me) -> DenseStore:
        out = DenseStore(
            lt=store.lt.at[slots].set(t),
            node=store.node.at[slots].set(me),
            val=store.val.at[slots].set(values),
            mod_lt=store.mod_lt.at[slots].set(t),
            mod_node=store.mod_node.at[slots].set(me),
            occupied=store.occupied.at[slots].set(True),
            tomb=store.tomb.at[slots].set(tombs),
        )
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        return out
    return jax.jit(step, donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _record_scatter(donate: bool, sharding=None):
    # mode="drop": callers pad the batch to a power of two with
    # slot == n_slots sentinels (stable jit shapes); those rows must
    # scatter nowhere.
    def step(store: DenseStore, slots, lt, node, val, mod_lt, mod_node,
             tomb) -> DenseStore:
        out = DenseStore(
            lt=store.lt.at[slots].set(lt, mode="drop"),
            node=store.node.at[slots].set(node, mode="drop"),
            val=store.val.at[slots].set(val, mode="drop"),
            mod_lt=store.mod_lt.at[slots].set(mod_lt, mode="drop"),
            mod_node=store.mod_node.at[slots].set(mod_node, mode="drop"),
            occupied=store.occupied.at[slots].set(True, mode="drop"),
            tomb=store.tomb.at[slots].set(tomb, mode="drop"),
        )
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        return out
    return jax.jit(step, donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _delete_scatter(donate: bool, sharding=None):
    def step(store: DenseStore, slots, t, me) -> DenseStore:
        out = DenseStore(
            lt=store.lt.at[slots].set(t),
            node=store.node.at[slots].set(me),
            val=store.val,
            mod_lt=store.mod_lt.at[slots].set(t),
            mod_node=store.mod_node.at[slots].set(me),
            occupied=store.occupied.at[slots].set(True),
            tomb=store.tomb.at[slots].set(True),
        )
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        return out
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def put_scatter(store: DenseStore, slots, values, t, me, tombs=None,
                donate: bool = False, sharding=None) -> DenseStore:
    """Batch put: scatter one shared HLC + values at ``slots``.
    ``tombs`` marks entries written as tombstones under the SAME batch
    stamp (a mixed putAll, crdt.dart:46-54 + delete-as-put-None)."""
    if tombs is None:
        tombs = jnp.zeros(values.shape, bool)
    with _obs_device.record("dense.put_scatter", dim=slots.shape[0],
                            donated=store.lt if donate else None):
        return _put_scatter(donate, sharding)(store, slots, values,
                                              tombs, t, me)


def record_scatter(store: DenseStore, slots, lt, node, val, mod_lt,
                   mod_node, tomb, donate: bool = False,
                   sharding=None) -> DenseStore:
    """Raw record writes preserving the given hlc/modified stamps —
    the putRecords storage primitive (crdt.dart:151-155): stores
    records verbatim, no LWW compare, no clock involvement."""
    with _obs_device.record("dense.record_scatter", dim=slots.shape[0],
                            donated=store.lt if donate else None):
        return _record_scatter(donate, sharding)(store, slots, lt,
                                                 node, val, mod_lt,
                                                 mod_node, tomb)


def delete_scatter(store: DenseStore, slots, t, me,
                   donate: bool = False, sharding=None) -> DenseStore:
    """Batch tombstone: scatter one shared HLC at ``slots``."""
    with _obs_device.record("dense.delete_scatter", dim=slots.shape[0],
                            donated=store.lt if donate else None):
        return _delete_scatter(donate, sharding)(store, slots, t, me)


@_functools.lru_cache(maxsize=None)
def _ingest_scatter(donate: bool, sharding=None):
    # mode="drop": the write combiner pads its flush lanes to a power
    # of two with slot == n_slots sentinels (stable jit shapes), same
    # trick as record_scatter.
    def step(store: DenseStore, slots, lt, val, tomb, me) -> DenseStore:
        out = DenseStore(
            lt=store.lt.at[slots].set(lt, mode="drop"),
            node=store.node.at[slots].set(me, mode="drop"),
            val=store.val.at[slots].set(val, mode="drop"),
            mod_lt=store.mod_lt.at[slots].set(lt, mode="drop"),
            mod_node=store.mod_node.at[slots].set(me, mode="drop"),
            occupied=store.occupied.at[slots].set(True, mode="drop"),
            tomb=store.tomb.at[slots].set(tomb, mode="drop"),
        )
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        return out
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def ingest_scatter(store: DenseStore, slots, lt, val, tomb, me,
                   donate: bool = False, sharding=None) -> DenseStore:
    """Fused write-combiner commit: like `put_scatter` but with a
    PER-ROW hlc lane (each staged group carries its own batch stamp
    from `Hlc.send_batch`) and mixed put/tombstone rows in one
    program. Writer attribution (``me``) and the modified stamps
    (``mod_lt = lt`` for local writes) broadcast in-jit, so the host
    ships 4 lanes per flush instead of `record_scatter`'s 7. One jit
    per (donate, sharding) pair; ``sharding`` pins the output store's
    NamedSharding so sharded commits land rows shard-locally."""
    with _obs_device.record("dense.ingest_scatter", dim=slots.shape[0],
                            donated=store.lt if donate else None):
        return _ingest_scatter(donate, sharding)(store, slots, lt, val,
                                                 tomb, me)


# --- tombstone epoch GC + online compaction (docs/STORAGE.md) ---
#
# Dense slots never reclaim on their own: a tombstone is lattice state
# (the delete must dominate concurrent writes), so it can only leave
# the store once the fleet stability watermark proves every peer's
# durable state already dominates it. `gc_purge` masks those stable
# tombstones out of every lane in one dispatch; `compact_remap` then
# spends the reclaimed slots, packing survivors to a dense prefix and
# rebuilding the digest tree in the same program. Both follow the
# (donate, sharding) factory idiom of the merge kernels above.


@_functools.lru_cache(maxsize=None)
def _gc_purge_jit(donate: bool, sharding=None):
    def step(store: DenseStore, floor_lt):
        purged = store.occupied & store.tomb & (store.lt <= floor_lt)
        keep = ~purged
        z64 = jnp.int64(0)
        z32 = jnp.int32(0)
        out = DenseStore(
            lt=jnp.where(keep, store.lt, z64),
            node=jnp.where(keep, store.node, z32),
            val=jnp.where(keep, store.val, z64),
            mod_lt=jnp.where(keep, store.mod_lt, z64),
            mod_node=jnp.where(keep, store.mod_node, z32),
            occupied=store.occupied & keep,
            tomb=store.tomb & keep,
        )
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        return out, jnp.sum(purged).astype(jnp.int32), purged
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def gc_purge(store: DenseStore, floor_lt, *, donate: bool = False,
             sharding=None) -> Tuple[DenseStore, jax.Array, jax.Array]:
    """Epoch tombstone purge: zero EVERY lane of tombstones whose
    record stamp is at or below ``floor_lt`` (inclusive — a durable
    watermark means delivered THROUGH the stamp) — ONE elementwise
    dispatch, no gather, no scatter.

    ``floor_lt`` must derive from a fleet stability watermark (every
    peer's durable watermark past the delete stamp, minus the HLC
    drift allowance — `GossipNode.stability_hlc`); the crdtlint
    ``purge-watermark-unfenced`` rule rejects call sites that invent
    one locally. A purged slot returns to the all-zero never-written
    state, so the caller must also arm its merge-side resurrection
    floor (`DenseCrdt.gc_purge`) — the kernel alone cannot stop a
    delayed pre-purge delta from re-occupying the slot. Returns
    ``(new_store, purged_count, purged_mask)``; the mask stays on
    device unless the caller (sanitizer, sem-column owner) fetches
    it."""
    with _obs_device.record("dense.gc_purge", dim=store.lt.shape[0],
                            donated=store.lt if donate else None):
        return _gc_purge_jit(donate, sharding)(store, floor_lt)


@_functools.lru_cache(maxsize=None)
def _compact_remap_jit(donate: bool, leaf_width: int, has_sem: bool,
                       sharding=None):
    # Imported here (not at module top): ops/digest.py imports
    # DenseStore from this module.
    from .digest import digest_levels_from_lanes

    def step(store: DenseStore, los, his, *sem):
        n = store.lt.shape[0]
        idx = jnp.arange(n, dtype=jnp.int64)
        # [S, N] span membership; spans are half-open, non-overlapping
        # (host-validated), padded to a power of two with lo == hi == 0
        # like dense_range_delta_mask.
        in_span = ((idx[None, :] >= los[:, None])
                   & (idx[None, :] < his[:, None]))
        keep = store.occupied
        k_in = in_span & keep[None, :]
        # Survivor rank within each span: running count along the slot
        # axis. Each slot is in at most one span, so summing the
        # masked per-span targets recovers its destination.
        rank = jnp.cumsum(k_in.astype(jnp.int64), axis=1)
        pos = los[:, None] + rank - 1
        tgt_in = jnp.sum(jnp.where(k_in, pos, 0), axis=0)
        moved = jnp.any(in_span, axis=0) & keep
        new_slot = jnp.where(moved, tgt_in, idx)
        translation = jnp.where(keep, new_slot, -1).astype(jnp.int32)
        # mode="drop": dropped rows target the out-of-range sentinel n,
        # same trick as record_scatter's padding.
        target = jnp.where(keep, new_slot, n).astype(jnp.int32)

        def scat(lane):
            return jnp.zeros(lane.shape, lane.dtype).at[target].set(
                lane, mode="drop")

        out = DenseStore(
            lt=scat(store.lt), node=scat(store.node),
            val=scat(store.val), mod_lt=scat(store.mod_lt),
            mod_node=scat(store.mod_node),
            occupied=scat(store.occupied), tomb=scat(store.tomb))
        new_sem = scat(sem[0]) if has_sem else None
        if sharding is not None:
            out = jax.lax.with_sharding_constraint(out, sharding)
        live = jnp.sum(keep.astype(jnp.int32))
        levels = digest_levels_from_lanes(
            out.lt, out.val, out.tomb, out.occupied, sem=new_sem,
            leaf_width=leaf_width)
        if has_sem:
            return out, new_sem, translation, live, levels
        return out, translation, live, levels
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def compact_remap(store: DenseStore, los, his, sem=None, *,
                  leaf_width: int, donate: bool = False, sharding=None):
    """Online compaction: remap surviving rows to the dense prefix of
    their span AND rebuild the digest-tree levels in ONE donated
    dispatch. ``(los, his)`` are half-open, non-overlapping slot spans
    (power-of-two padded with empty ``lo == hi == 0`` spans); rows
    outside every span keep their slot, so per-partition/per-shard
    compaction is range-preserving by construction. ``sem`` is the
    optional per-slot semantics tag column, remapped with the rows so
    typed lanes keep their kernels.

    Returns ``(new_store[, new_sem], translation, live_count,
    digest_levels)`` — ``translation[old] = new`` (int32, ``-1`` for
    unoccupied slots) is what the host layers rewrite against:
    `KeyedDenseCrdt`'s intern map and the routing layer's range arcs.
    Slot identity is wire identity, so a full-store remap is only
    externally safe for single-owner stores or when every replica
    applies the identical translation (docs/STORAGE.md)."""
    with _obs_device.record("dense.compact_remap",
                            dim=store.lt.shape[0],
                            donated=store.lt if donate else None):
        if sem is not None:
            return _compact_remap_jit(donate, leaf_width, True,
                                      sharding)(store, los, his, sem)
        return _compact_remap_jit(donate, leaf_width, False,
                                  sharding)(store, los, his)

"""Backend-agnostic CRDT conformance kit (C14) — EXPORTED API.

Port of the reference's exported parameterized suite
`test/crdt_test.dart:7-132`: any storage backend (in-tree or
out-of-tree, the README.md:39 plugin pattern) subclasses
:class:`CrdtConformance`, provides ``make_crdt()``, and inherits the
full behavioral test set under pytest — the same mechanism the
reference uses to keep external backends like hive_crdt conformant
(CHANGELOG.md:16). :class:`FakeClock` is the deterministic wall clock
every test should inject (the reference's own millis-injection pattern,
hlc_test.dart:185).
"""


from __future__ import annotations

import itertools

from crdt_tpu import Crdt
# Fault-injection siblings of this kit: a backend proves CONFORMANCE
# here, and proves ROBUSTNESS against the scheduled-misbehavior proxy.
from crdt_tpu.testing_faults import (FaultProxy, FaultSchedule,  # noqa: F401
                                     ProxyFarm, ScriptedSchedule)


class FakeClock:
    """Deterministic, strictly advancing wall clock for tests.

    The reference's tests order events with real sleeps
    (map_crdt_test.dart:248); injecting millis is the deterministic
    equivalent and is the reference's own pattern for clock tests
    (hlc_test.dart:185).
    """

    def __init__(self, start: int = 1_700_000_000_000, step: int = 1):
        self._millis = start
        self._step = step

    def __call__(self) -> int:
        self._millis += self._step
        return self._millis

    def advance(self, millis: int) -> None:
        self._millis += millis

    @property
    def millis(self) -> int:
        return self._millis


class CountingClock(FakeClock):
    """`FakeClock` that also counts reads.

    Tick-accounting differentials are built on this: two backends fed
    the same op sequence through counting clocks must consume the SAME
    number of wall reads, or their clocks (and so their HLC stamps)
    silently diverge under any injected clock — the failure mode the
    shared ``Crdt._decode_wall_millis`` helper exists to prevent."""

    def __init__(self, start: int = 1_700_000_000_000, step: int = 1):
        super().__init__(start, step)
        self.reads = 0

    def __call__(self) -> int:
        self.reads += 1
        return super().__call__()


def assert_dense_stores_equal(a, b, where: str = "store") -> None:
    """Lane-exact equality of two `DenseStore`s on OCCUPIED slots (an
    unoccupied slot's lane contents are unobservable through
    `record_map`, so executors may differ there). Shared by the test
    suite and the on-chip validation harness — one definition of
    store equality."""
    import numpy as np
    occ = np.asarray(a.occupied)
    np.testing.assert_array_equal(occ, np.asarray(b.occupied),
                                  err_msg=f"{where}: occupied")
    for lane in ("lt", "node", "val", "mod_lt", "mod_node", "tomb"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, lane))[occ],
            np.asarray(getattr(b, lane))[occ],
            err_msg=f"{where}: {lane}")


class SemanticsConformance:
    """Per-semantics lattice conformance over the typed dense surface
    (`crdt_tpu.semantics`, docs/TYPES.md). The registry's law search
    proves each kernel algebraically; this suite proves the MODEL
    wiring — per-slot tag column, combiner routing, delta export and
    merge — delivers those laws end to end, for EVERY registered
    semantics: the tests iterate `semantics.names()`, so registering
    a new type without extending the workload table fails the suite
    instead of silently skipping the newcomer.

    Subclass and implement ``make_dense(node_id)`` returning an empty
    typed-capable dense model (``DenseCrdt``-shaped surface).
    Counters keep one WRITER per slot — the dense counter contract
    (`DenseCrdt.counter_add`): concurrent same-slot increments join
    by per-lane max, not addition.
    """

    n_slots = 64

    def make_dense(self, node_id):
        raise NotImplementedError

    # --- helpers ---

    def _pair(self, sem: str):
        a, b = self.make_dense("a"), self.make_dense("b")
        for c in (a, b):
            if sem != "lww":   # lww IS the untyped default (tag 0)
                c.set_semantics([0, 1], sem)
        return a, b

    @staticmethod
    def _write(c, sem: str, variant: int) -> None:
        """Replica-``variant`` (0 or 1) workload for one semantics."""
        if sem == "lww":
            c.put_batch([0, 1], [10 + variant, 20 + variant])
        elif sem == "gcounter":
            c.counter_add(variant, 5 + variant)
            c.counter_add(variant, 2)
        elif sem == "pncounter":
            c.counter_add(variant, 7)
            c.counter_add(variant, -(3 + variant))
        elif sem == "orset":
            c.orset_add(0, 1 + variant)
            if variant:
                c.orset_add(0, 3)
                c.orset_remove(0, 3)
        else:
            assert sem == "mvreg", \
                f"no conformance workload for registered " \
                f"semantics {sem!r} — extend SemanticsConformance"
            c.mvreg_put(0, 100 + variant)

    @staticmethod
    def _exchange(a, b) -> None:
        """Full bidirectional delta exchange (cold-start shape: both
        sides export everything — immune to same-millisecond watermark
        exclusion, which is a clock concern, not a semantics one)."""
        cs_a, ids_a = a.export_delta()
        cs_b, ids_b = b.export_delta()
        b.merge(cs_a, ids_a)
        a.merge(cs_b, ids_b)

    @staticmethod
    def _assert_lanes_equal(a, b, where: str) -> None:
        """Replica-visible lane equality: ``modified`` stamps are
        local-only and unoccupied slots are unobservable (ordinal
        remaps legitimately rewrite them), so compare (lt, node, val,
        tomb) at occupied slots only."""
        import numpy as np
        sa, sb = a.store, b.store
        occ = np.asarray(sa.occupied)
        np.testing.assert_array_equal(
            occ, np.asarray(sb.occupied), err_msg=f"{where}: occupied")
        for lane in ("lt", "node", "val", "tomb"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sa, lane))[occ],
                np.asarray(getattr(sb, lane))[occ],
                err_msg=f"{where}: {lane}")

    # --- the per-semantics laws, end to end ---

    def test_every_registered_semantics_converges(self):
        from crdt_tpu.semantics import names
        for sem in names():
            a, b = self._pair(sem)
            self._write(a, sem, 0)
            self._write(b, sem, 1)
            self._exchange(a, b)
            self._assert_lanes_equal(a, b, f"{sem}: converged")
            if sem in ("gcounter", "pncounter"):
                assert (a.counter_value(0) == b.counter_value(0)
                        and a.counter_value(1) == b.counter_value(1)
                        ), sem
            elif sem == "orset":
                assert (a.orset_members(0) == b.orset_members(0)
                        == frozenset({1, 2})), sem
            elif sem == "mvreg":
                got = a.mvreg_get(0)
                assert got == b.mvreg_get(0) and got != (), sem

    def test_every_registered_semantics_idempotent_redelivery(self):
        import numpy as np
        from crdt_tpu.semantics import names
        for sem in names():
            a, b = self._pair(sem)
            self._write(a, sem, 0)
            self._write(b, sem, 1)
            cs, ids = a.export_delta()
            b.merge(cs, ids)
            before = b.store
            b.merge(cs, ids)   # exact redelivery: a no-op join
            for lane in before._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(before, lane)),
                    np.asarray(getattr(b.store, lane)),
                    err_msg=f"{sem}: redelivery changed {lane}")

    def test_every_registered_semantics_merge_order_commutes(self):
        from crdt_tpu.semantics import names
        for sem in names():
            a, b = self._pair(sem)
            self._write(a, sem, 0)
            self._write(b, sem, 1)
            da = a.export_delta()
            db = b.export_delta()
            # receiver names sort AFTER both writers so the interned
            # node tables end identical on both orders
            c1, c2 = self.make_dense("c1"), self.make_dense("c2")
            for c in (c1, c2):
                if sem != "lww":
                    c.set_semantics([0, 1], sem)
            c1.merge(*da)
            c1.merge(*db)
            c2.merge(*db)
            c2.merge(*da)
            self._assert_lanes_equal(c1, c2, f"{sem}: merge order")


class CrdtConformance:
    """Inherit and implement ``make_crdt`` to run the conformance suite."""

    node_id = "abc"

    def make_crdt(self) -> Crdt:
        raise NotImplementedError

    # --- Basic (crdt_test.dart:13-94) ---

    def test_node_id(self):
        assert self.make_crdt().node_id == self.node_id

    def test_empty(self):
        crdt = self.make_crdt()
        assert crdt.is_empty
        assert crdt.length == 0
        assert crdt.map == {}
        assert crdt.keys == []
        assert crdt.values == []

    def test_one_record(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        assert not crdt.is_empty
        assert crdt.length == 1
        assert crdt.map == {"x": 1}
        assert crdt.keys == ["x"]
        assert crdt.values == [1]

    def test_empty_after_deleted_record(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        crdt.delete("x")
        assert crdt.is_empty
        assert crdt.length == 0
        assert crdt.map == {}
        assert crdt.keys == []
        assert crdt.values == []

    def test_put(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        assert crdt.get("x") == 1

    def test_update_existing(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        crdt.put("x", 2)
        assert crdt.get("x") == 2

    def test_put_many(self):
        crdt = self.make_crdt()
        crdt.put_all({"x": 2, "y": 3})
        assert crdt.get("x") == 2
        assert crdt.get("y") == 3

    def test_put_all_single_timestamp(self):
        # One send per batch: all records share one HLC (crdt.dart:50-52).
        crdt = self.make_crdt()
        crdt.put_all({"x": 2, "y": 3})
        assert crdt.get_record("x").hlc == crdt.get_record("y").hlc

    def test_delete_value(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        crdt.put("y", 2)
        crdt.delete("x")
        assert crdt.is_deleted("x") is True
        assert crdt.is_deleted("y") is False
        assert crdt.get("x") is None
        assert crdt.get("y") == 2

    def test_is_deleted_missing_key(self):
        assert self.make_crdt().is_deleted("nope") is None

    def test_clear(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        crdt.put("y", 2)
        crdt.clear()
        assert crdt.is_deleted("x") is True
        assert crdt.is_deleted("y") is True
        assert crdt.get("x") is None
        assert crdt.get("y") is None

    def test_clear_purge(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        crdt.clear(purge=True)
        assert crdt.record_map() == {}

    def test_contains_key(self):
        crdt = self.make_crdt()
        crdt.put("x", 1)
        assert crdt.contains_key("x")
        assert not crdt.contains_key("y")

    # --- Watch (crdt_test.dart:96-131) ---

    def test_watch_all_changes(self):
        crdt = self.make_crdt()
        stream = crdt.watch().record()
        crdt.put("x", 1)
        crdt.put("y", 2)
        got = {(e.key, e.value) for e in stream.events}
        assert {("x", 1), ("y", 2)} <= got

    def test_watch_key(self):
        crdt = self.make_crdt()
        stream = crdt.watch(key="y").record()
        crdt.put("x", 1)
        crdt.put("y", 2)
        assert [(e.key, e.value) for e in stream.events] == [("y", 2)]

    def test_watch_put_all_unordered(self):
        # putAll emits one event per record; delivery order is
        # unspecified (the reference asserts emitsInAnyOrder,
        # crdt_test.dart:106-114).
        crdt = self.make_crdt()
        stream = crdt.watch().record()
        crdt.put_all({"x": 1, "y": 2, "z": 3})
        assert sorted((e.key, e.value) for e in stream.events) == \
            [("x", 1), ("y", 2), ("z", 3)]

    def test_watch_delete_emits_none(self):
        # Deletes notify with a null value (crdt_test.dart:116-122:
        # MapEntry(key, null)).
        crdt = self.make_crdt()
        crdt.put("x", 1)
        stream = crdt.watch().record()
        crdt.delete("x")
        assert ("x", None) in [(e.key, e.value) for e in stream.events]

    def test_watch_merge_emits_winners_only(self):
        # Merge-driven reactivity: adopted records reach putRecords and
        # emit (map_crdt.dart:33-39); LWW losers never do. Includes a
        # merged-in tombstone (value None event) and the idempotent
        # re-merge (no events).
        cs1, cs2, _ = self._seeded_changesets()
        crdt = self.make_crdt()
        stream = crdt.watch().record()
        crdt.merge(dict(cs1))          # both records new -> both emit
        assert sorted((e.key, e.value) for e in stream.events) == \
            [("x", 1), ("y", 7)]
        crdt.merge(dict(self._seeded_changesets()[0]))  # idempotent
        assert len(stream.events) == 2  # no new events
        # cs2: "x" ties on logical time, nodeB > nodeA -> remote wins;
        # "z" is a new tombstone -> merge-driven None event.
        crdt.merge(dict(cs2))
        assert sorted(((e.key, e.value) for e in stream.events[2:]),
                      key=lambda kv: kv[0]) == [("x", 2), ("z", None)]

    def test_watch_key_filter_under_merge(self):
        # Per-key filtering applies to merge-driven events too
        # (crdt_test.dart:124-131 shape, driven through merge).
        cs1, _, cs3 = self._seeded_changesets()
        crdt = self.make_crdt()
        stream = crdt.watch(key="y").record()
        crdt.merge(dict(cs1))          # y=7 wins, x=1 wins (filtered out)
        crdt.merge(dict(cs3))          # y=9 wins, z=4 wins (filtered out)
        assert [(e.key, e.value) for e in stream.events] == \
            [("y", 7), ("y", 9)]

    def test_watch_bulk_merge_events(self):
        # Bulk-merge reactivity at batch size: winners (and ONLY
        # winners) emit — new keys, newer updates, merged-in
        # tombstones — while LWW losers stay silent; a key-filtered
        # stream sees exactly its key; an idempotent re-merge emits
        # nothing. Pins the batch emission path the vectorized
        # backends use (hub.add_batch), not just single-record adds.
        from crdt_tpu import Hlc, Record
        base = 1_700_000_000_000
        crdt = self.make_crdt()
        crdt.put_all({f"mine{i}": 100 + i for i in range(20)})
        mk = lambda ms, v: Record(Hlc(ms, 0, "peer"), v,
                                  Hlc(ms, 0, "peer"))
        cs = {}
        for i in range(20):
            cs[f"mine{i}"] = mk(base - 1000, -1)     # losers: too old
        for i in range(20):
            cs[f"new{i}"] = mk(base + 100 + i,
                               None if i % 5 == 0 else i)
        whole = crdt.watch().record()
        keyed = crdt.watch(key="new7").record()
        crdt.merge(dict(cs))
        got = sorted((e.key, e.value) for e in whole.events)
        want = sorted((f"new{i}", None if i % 5 == 0 else i)
                      for i in range(20))
        assert got == want, f"winner events wrong: {got[:5]}..."
        assert [(e.key, e.value) for e in keyed.events] == [("new7", 7)]
        crdt.merge(dict(cs))                          # idempotent
        assert len(whole.events) == 20
        assert len(keyed.events) == 1

    # --- Merge algebra: the CRDT laws (SURVEY.md §5 race-detection
    # equivalent — commutativity/associativity/idempotence under
    # permutation, map_crdt_test.dart:252-269 in spirit) ---

    def _seeded_changesets(self):
        from crdt_tpu import Hlc, Record
        base = 1_700_000_000_000
        mk = lambda ms, c, n, v: Record(Hlc(ms, c, n), v, Hlc(ms, c, n))
        cs1 = {"x": mk(base + 5, 0, "nodeA", 1), "y": mk(base + 1, 0, "nodeA", 7)}
        cs2 = {"x": mk(base + 5, 0, "nodeB", 2), "z": mk(base + 3, 1, "nodeB", None)}
        cs3 = {"y": mk(base + 9, 2, "nodeC", 9), "z": mk(base + 3, 0, "nodeC", 4)}
        return [cs1, cs2, cs3]

    def test_merge_commutative_associative(self):
        changesets = self._seeded_changesets()
        results = []
        for perm in itertools.permutations(range(3)):
            crdt = self.make_crdt()
            for i in perm:
                crdt.merge(dict(self._seeded_changesets()[i]))
            results.append({k: (r.hlc, r.value)
                            for k, r in crdt.record_map().items()})
        assert all(r == results[0] for r in results[1:])

    def test_merge_idempotent(self):
        cs = self._seeded_changesets()[0]
        crdt = self.make_crdt()
        crdt.merge(dict(cs))
        snapshot = {k: (r.hlc, r.value) for k, r in crdt.record_map().items()}
        crdt.merge(dict(self._seeded_changesets()[0]))
        again = {k: (r.hlc, r.value) for k, r in crdt.record_map().items()}
        assert snapshot == again

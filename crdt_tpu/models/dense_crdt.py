"""DenseCrdt — fully device-resident LWW map over a dense integer key
space.

`TpuMapCrdt` is the drop-in general backend (arbitrary keys/values,
host dict for key↔slot); this model is the high-throughput shape: keys
ARE slot indices ``[0, n_slots)`` and values are int64 scalars (or
indices into an application-side table, SURVEY.md §7 hard part 4), so
every operation is a batched array op with zero per-record host work —
the shape the benchmark's billions-of-merges/sec numbers come from.

Replication model (C9/C10 on arrays):

- ``export_delta(since)`` → ``(DenseChangeset, node_ids)`` — the
  outbound half of the anti-entropy round; ordinals in the changeset
  index into the accompanying ``node_ids`` list so peers with different
  interning histories stay compatible.
- ``merge(changeset, node_ids)`` — remaps peer ordinals into the local
  `NodeTable` (one small host gather), then runs the fused fan-in
  lattice join. Recv guards raise the reference's exception types
  (hlc.dart:164-189).
- ``sync_dense(a, b)`` — the push/pull round (test/map_crdt_test.dart:
  273-279 semantics, inclusive delta bound).

The columnar store round-trips through `crdt_tpu.checkpoint.save_dense`.
"""

from __future__ import annotations

from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import crdt_json
from ..analysis import sanitizer as _sanitizer
from ..hlc import (ClockDriftException, DuplicateNodeException, Hlc,
                   wall_clock_millis)
from ..ops.dense import (DenseChangeset, DenseStore, FaninResult, _NEG,
                         delete_scatter, dense_delta_mask,
                         dense_max_logical_time,
                         empty_dense_store, fanin_step, fanin_stream,
                         merge_repack_step, pad_replica_rows, put_scatter,
                         sparse_fanin_step, store_to_changeset)
from ..ops.merge import recv_guards
from ..ops.packing import NodeTable, PackedDelta, pack_into_arena
from ..record import (KeyDecoder, KeyEncoder, Record, ValueDecoder,
                      ValueEncoder)
from ..utils.stats import MergeStats, merge_annotation
from ..watch import ChangeHub, ChangeStream


class PipelinedGuardError(Exception):
    """A clock guard tripped inside a ``DenseCrdt.pipelined()`` window.

    Pipelined merges trade first-offender diagnostics for zero
    per-merge host synchronization: guard flags accumulate on device
    and are checked once at the window's end, so all this error can
    say is WHICH guard class fired. Re-run the same batches
    unpipelined for the exact sequential diagnosis (the store already
    holds the merged state — merge is idempotent, a re-run is safe).
    """


class _PipeState:
    """Device-resident clock state threaded across a pipelined window."""

    __slots__ = ("canonical", "any_bad", "overflow", "drift",
                 "val_overflow", "first_flag_idx", "merges",
                 "exact", "ex_have", "ex_dup", "ex_lt", "ex_caf",
                 "ex_wall")

    def __init__(self, canonical_lt: int, exact: bool = False):
        self.canonical = jnp.int64(canonical_lt)
        self.any_bad = jnp.asarray(False)
        self.overflow = jnp.asarray(False)
        self.drift = jnp.asarray(False)
        self.val_overflow = jnp.asarray(False)
        # Index (0-based, in window order) of the first merge that set
        # ANY flag — the flush names it so "re-run unpipelined" can
        # start at the right batch instead of replaying the window.
        self.first_flag_idx = jnp.int32(-1)
        self.merges = 0
        # exact mode: the first offender's own fields, accumulated on
        # device in sequential visit order (recv_guards per merge,
        # seeded with the threaded canonical — identical flags to the
        # unpipelined path, no supersets). ex_wall is the OFFENDING
        # merge's wall read, captured alongside ex_lt so exception
        # payloads can't pair one merge's record with another's wall.
        self.exact = exact
        self.ex_have = jnp.asarray(False)
        self.ex_dup = jnp.asarray(False)
        self.ex_lt = jnp.int64(0)       # offending record's logicalTime
        self.ex_caf = jnp.int64(0)      # canonical just before it
        self.ex_wall = jnp.int64(0)     # that merge's wall read

    def note(self, flags, idx: Optional[int] = None) -> None:
        """Attribute freshly-raised flags to window slot ``idx``
        (default: the current merge counter)."""
        i = self.merges if idx is None else idx
        newly = ((self.first_flag_idx < 0) & flags).astype(jnp.bool_)
        self.first_flag_idx = jnp.where(newly, jnp.int32(i),
                                        self.first_flag_idx)


@jax.jit
def _pipe_exact_guards(lt, node, valid, canonical_lt, local_node, wall):
    """One exact recv-guard pass for a pipelined merge (the r-major
    running-cummax semantics of `ops.merge.recv_guards`, seeded with
    the THREADED device canonical — flag-identical to the unpipelined
    path) plus the offender's logicalTime, fetched in-jit."""
    any_b, first_bad, first_is_dup, caf = recv_guards(
        lt, node, valid, canonical_lt, local_node, wall)
    return any_b, lt.reshape(-1)[first_bad], first_is_dup, caf


class DenseCrdt:
    """LWW-map CRDT over slots ``[0, n_slots)`` with int64 values."""

    def __init__(self, node_id: Any, n_slots: int,
                 wall_clock: Optional[Callable[[], int]] = None,
                 store: Optional[DenseStore] = None,
                 node_ids: Optional[Sequence[Any]] = None,
                 executor: str = "auto", value_width: int = 64):
        if executor not in ("auto", "xla", "pallas", "pallas-interpret"):
            raise ValueError(f"unknown executor {executor!r}")
        if value_width not in (64, 32):
            raise ValueError(f"value_width must be 64 or 32, got "
                             f"{value_width}")
        # value_width=32 — the value-ref mode: values are int32-range
        # scalars or indices into an application-side payload table
        # (SURVEY.md §7 hard part 4). The Mosaic executor then carries
        # ONE int32 val lane (15 B/merge instead of 19; ~1.27× the
        # distinct-row throughput) and sign-extends into the int64
        # storage lane in-kernel. Out-of-range values are rejected:
        # host-side writes immediately, device changesets via a lazily
        # checked overflow flag (no extra sync).
        self._value_width = value_width
        if executor in ("pallas", "pallas-interpret"):
            # Validate eagerly (mirroring grow()): deferring to the
            # first merge's kernel-level check would mis-run silently
            # under `python -O` when that check was an assert.
            from ..ops.pallas_merge import TILE
            if n_slots % TILE:
                raise ValueError(
                    f"executor={executor!r} needs n_slots % {TILE} == 0; "
                    f"got {n_slots}")
        self._executor = executor
        self._node_id = node_id
        self._wall_clock = wall_clock or wall_clock_millis
        # A seeded store's ordinal lanes index sorted(node_ids); build
        # that exact table FIRST, then intern our own id — re-encoding
        # the lanes if the new id sorts into the middle (a resume under
        # a fresh node id must not shift attribution).
        self._table = NodeTable(node_ids or [])
        # A caller-supplied store counts as escaped: the caller may
        # still hold it, so write scatters must not donate its buffers.
        self._store_escaped = store is not None
        # pack_since cache (watermark key -> packed delta); must exist
        # before the first store assignment — the _store setter clears
        # it on every replacement.
        self._pack_cache: "OrderedDict[Any, Any]" = OrderedDict()
        # digest_tree cache: one (key, DigestTree) pair, same
        # invalidation discipline as the pack cache (docs/ANTIENTROPY.md).
        self._digest_cache: Optional[Tuple[Any, Any]] = None
        # Tombstone-GC state (docs/STORAGE.md). The generation counts
        # store replacements; gc_purge/compact advance it WITHOUT
        # advancing the canonical clock, so cache keys carry it — a
        # purely clock-keyed cache would alias across a purge. The
        # floor is the armed resurrection fence (merge paths drop
        # sub-floor rows targeting empty slots); the last-floor latch
        # makes an unadvanced watermark cost zero dispatches.
        self._store_gen = 0
        self._gc_floor_lt = 0
        self._last_gc_floor_lt = 0
        self._gc_purged: Optional[Tuple[np.ndarray, int]] = None
        # Device bool[n_slots]: slots epoch GC physically purged.
        # The resurrection fence drops sub-floor inbound rows ONLY on
        # these slots — an empty slot that was never purged has
        # nothing to resurrect, and legitimately receives old rows
        # for the first time (a migration stream re-homing an arc, a
        # peer's initial full sync). Retired by compact (the remap
        # invalidates slot identity).
        self._gc_fence_dev = None
        self._store = store if store is not None else empty_dense_store(
            n_slots)
        if self._store.n_slots != n_slots:  # must survive `python -O`
            raise ValueError(
                f"store holds {self._store.n_slots} slots but "
                f"n_slots={n_slots}")
        if node_id not in self._table:
            self._intern_ids([node_id])
        self.stats = MergeStats().register(backend="DenseCrdt",
                                           node=str(node_id))
        self._hub = ChangeHub()
        self._pipe: Optional[_PipeState] = None
        # Active ingest() write combiner, or None (models/ingest.py).
        self._ingest = None
        self._pending_val_overflow = None
        # Per-slot semantics tags (`crdt_tpu.semantics`, docs/TYPES.md):
        # None == every slot is LWW (tag 0), the seed behavior. The
        # version counter keys outbound pack-cache entries, so a
        # semantics migration invalidates cached packs even when the
        # store lanes (and thus the canonical clock) are unchanged.
        self._sem: Optional[np.ndarray] = None
        self._sem_dev = None
        self._sem_version = 0
        self.refresh_canonical_time()

    # --- clock (crdt.dart:8-33,114-121) ---

    @property
    def node_id(self) -> Any:
        return self._node_id

    @property
    def n_slots(self) -> int:
        return self._store.n_slots

    @property
    def canonical_time(self) -> Hlc:
        return self._canonical_time

    @property
    def _store(self) -> DenseStore:
        return self._store_lanes

    @_store.setter
    def _store(self, store: DenseStore) -> None:
        # The ONE choke point every mutation path shares (puts,
        # deletes, merges, grow, intern remaps): any store replacement
        # invalidates cached outbound packs, so `pack_since` can trust
        # a cache hit without re-deriving what changed.
        self._store_lanes = store
        self._store_gen = self.__dict__.get("_store_gen", 0) + 1
        cache = self.__dict__.get("_pack_cache")
        if cache:
            cache.clear()
        if self.__dict__.get("_digest_cache") is not None:
            self._digest_cache = None

    @property
    def store(self) -> DenseStore:
        """Live store lanes. Reading this marks the snapshot as
        escaped, which disables buffer donation on subsequent
        `put_batch`/`delete_batch` calls until the store is next
        replaced — a snapshot you hold stays readable."""
        self.drain_ingest()
        self._store_escaped = True
        return self._store

    @property
    def store_generation(self) -> int:
        """Monotonic count of store replacements. Every mutation lands
        through the ``_store`` setter and bumps it — including
        `gc_purge`/`compact`, which do NOT advance the canonical clock,
        so pack/digest cache keys fold this in (docs/STORAGE.md)."""
        return self._store_gen

    @property
    def gc_floor(self) -> int:
        """The armed resurrection fence: the highest purge floor (a
        packed logical time) any `gc_purge` ran at, or 0. Merge paths
        drop inbound rows below it that target unoccupied slots."""
        return self._gc_floor_lt

    def refresh_canonical_time(self) -> None:
        self.drain_ingest()
        self._canonical_time = Hlc.from_logical_time(
            int(dense_max_logical_time(self._store)), self._node_id)

    def _canonical_lt(self) -> jax.Array:
        """The canonical logicalTime as a device scalar — the live
        pipeline clock inside a ``pipelined()`` window, the host
        ``Hlc`` otherwise."""
        if self._pipe is not None:
            return self._pipe.canonical
        return jnp.int64(self._canonical_time.logical_time)

    @contextmanager
    def pipelined(self, exact_guards: bool = False):
        """Zero-host-sync merge window: inside it, ``merge`` /
        ``merge_many`` thread the canonical clock as a DEVICE scalar
        (the final send bump runs on device, `ops.merge.send_step`)
        and accumulate guard flags instead of fetching them — no
        device→host round trip per merge, which on remote-proxied
        chips is the dominant per-call cost. On exit, ONE readback
        materializes the clock and raises `PipelinedGuardError` if any
        recv/send guard fired during the window (coarse by design —
        the docstring there explains the trade).

        Semantic differences from unpipelined merges, stated plainly:

        - **Merges land optimistically.** An unpipelined merge with a
          real guard violation refuses the changeset (store
          untouched); a pipelined window has already applied it by
          the time the flush reports the flag. The lattice join is
          monotone either way, but clock-policy-violating records are
          IN the store when the error raises.
        - **Flags may be spurious.** The Mosaic/sharded executors'
          guard flags are documented supersets (a record the exact
          sequential order shields can still flag); unpipelined
          merges clear those by exact host recomputation, which needs
          the changesets — gone by flush time. A
          `PipelinedGuardError` therefore means "re-run unpipelined
          to find out": a clean re-run (merge is idempotent — the
          state is already merged) proves the flag spurious.
        - Wall-read counts match unpipelined merges, but the reads
          feed device ops; exception payloads are coarse.
        - **An active watch subscriber re-introduces a per-merge
          readback.** Change events are host-side by design (the win
          mask and winner lanes must be fetched to emit), so a window
          with live subscribers runs at unpipelined latency — the
          events themselves stay correct.

        Store lanes and the canonical clock are bit-identical to the
        same merges issued unpipelined (differentially tested).
        Local writes (`put_batch` etc.) are refused inside the
        window — they need the host clock.

        ``exact_guards=True`` trades one extra device pass per merge
        (the r-major running-cummax `recv_guards`, seeded with the
        threaded canonical — flag-identical to the unpipelined path)
        for EXACT diagnostics: no spurious flags, and the flush raises
        the reference's own typed exceptions
        (`DuplicateNodeException`/`ClockDriftException`) with the
        unpipelined payloads, naming the offending merge. The window
        contract is unchanged in one respect: merges have already
        LANDED when the flush raises (optimistic application)."""
        if self._pipe is not None:
            raise RuntimeError("pipelined() windows do not nest")
        # A pipelined window threads the canonical as a device scalar
        # seeded HERE; staged ingest rows would otherwise commit with
        # stamps the window never sees — barrier first.
        self.drain_ingest()
        import sys as _sys
        self._pipe = _PipeState(self._canonical_time.logical_time,
                                exact=exact_guards)
        try:
            yield self
        finally:
            pipe, self._pipe = self._pipe, None
            (lt, any_bad, overflow, drift, val_ovf, first_idx,
             ex_have, ex_dup, ex_lt, ex_caf, ex_wall) = jax.device_get(
                (pipe.canonical, pipe.any_bad, pipe.overflow,
                 pipe.drift, pipe.val_overflow, pipe.first_flag_idx,
                 pipe.ex_have, pipe.ex_dup, pipe.ex_lt, pipe.ex_caf,
                 pipe.ex_wall))
            self._canonical_time = Hlc.from_logical_time(
                int(lt), self._node_id)
            # Never shadow an in-flight exception from the window
            # body — the guard report matters less than the error
            # that actually interrupted the caller. (A bare `return`
            # here would SWALLOW it: finally-block semantics.)
            in_flight = _sys.exc_info()[0] is not None
            def _coarse_report(include_recv: bool) -> None:
                kinds = [k for k, f in (
                    ("recv-guard (duplicate-node or drift)",
                     any_bad and include_recv),
                    ("recv-guard (exact: "
                     + ("duplicate-node" if bool(ex_dup) else "drift")
                     + ")", pipe.exact and ex_have),
                    ("send counter overflow", overflow),
                    ("send drift", drift),
                    ("value-ref overflow (records with values past "
                     "int32 were SKIPPED, not merged; re-sync from "
                     "the peer with a value_width=64 replica)",
                     val_ovf)) if bool(f)]
                raise PipelinedGuardError(
                    f"guards tripped in pipelined window: "
                    f"{', '.join(kinds)}; first flagged at merge "
                    f"#{int(first_idx)} of {pipe.merges} (0-based, "
                    "window order)"
                    + ("" if pipe.exact else
                       "; possibly spurious (superset flags) — re-run "
                       "from that batch unpipelined for the exact "
                       "diagnosis, or open the window with "
                       "exact_guards=True"))

            if not in_flight:
                if not pipe.exact:
                    if (bool(any_bad) or bool(overflow) or bool(drift)
                            or bool(val_ovf)):
                        _coarse_report(include_recv=True)
                else:
                    # Exact-mode priority mirrors the unpipelined
                    # in-merge ordering: a value-overflow rejects
                    # before guard handling (its "records were
                    # SKIPPED" report must never be eaten by a typed
                    # raise); the recv guard preempts the send bump
                    # (send flags on an offending merge are a
                    # consequence of optimistic application, not the
                    # diagnosis).
                    if bool(val_ovf):
                        _coarse_report(include_recv=False)
                    if bool(ex_have):
                        # The unpipelined exception types and payloads
                        # (the merges are already in the store —
                        # window contract); ex_wall is the offending
                        # merge's own wall read.
                        if bool(ex_dup):
                            raise DuplicateNodeException(
                                str(self._node_id))
                        raise ClockDriftException(int(ex_lt) >> 16,
                                                  int(ex_wall))
                    if bool(overflow) or bool(drift):
                        _coarse_report(include_recv=False)

    # --- ingest fast lane (models/ingest.py, docs/INGEST.md) ---

    @contextmanager
    def ingest(self, auto_flush_rows: int = 1 << 16):
        """Write-combining window: inside it, ``put_batch`` /
        ``delete_batch`` (and everything routed through them —
        `KeyedDenseCrdt.put`, ``clear``) stage into host-side columnar
        buffers instead of dispatching a scatter per call. Staged rows
        commit as ONE fused device program (`ops.dense.ingest_scatter`)
        stamped by ONE vectorized `Hlc.send_batch` — each staged call
        keeps its own strictly-later HLC, so per-record LWW order is
        exactly the unbatched outcome. Commits are non-blocking
        (double-buffered: the host stages the next backlog while the
        previous flush executes on device).

        Flush triggers: the backlog reaching ``auto_flush_rows``; any
        merge/pack/serialization/snapshot barrier (`drain_ingest`);
        an explicit ``wc.flush()``; window exit.

        Visibility: point reads (``get`` / ``contains_slot`` /
        ``is_deleted``) and ``count_modified_since`` answer from the
        staging overlay — read-your-writes without a flush. Every
        other surface drains first, so nothing outside the window can
        observe a store missing staged rows. Change events fire at
        COMMIT with the winning post-dedup value per slot.

        Semantic differences from unbatched writes, stated plainly:
        staged calls share one flush-time wall read (one `send_batch`
        counter run) instead of one wall read per call, so injected
        clocks tick differently — which is why the combiner is opt-in
        rather than always-on. Refused inside ``pipelined()`` windows
        (local writes need the host clock there too); opening a
        pipelined window inside an ingest window drains first.

        Yields the `WriteCombiner` (exposes ``pending_rows``,
        ``flush()``, ``flushes``/``rows_committed`` counters)."""
        self._refuse_in_pipeline("ingest")
        if self._ingest is not None:
            raise RuntimeError("ingest() windows do not nest")
        from .ingest import WriteCombiner
        import sys as _sys
        wc = WriteCombiner(self, auto_flush_rows=auto_flush_rows)
        self._ingest = wc
        try:
            yield wc
        finally:
            try:
                wc.flush("exit")
            except Exception:
                # Never shadow the exception that interrupted the
                # window body (same contract as pipelined()); with no
                # in-flight error the flush failure IS the error.
                if _sys.exc_info()[0] is None:
                    raise
            finally:
                self._ingest = None

    def drain_ingest(self) -> bool:
        """Commit any staged ingest-window writes NOW. No-op outside a
        window (returns False). Every merge / pack / serialization /
        checkpoint / bulk-read surface calls this first — the barrier
        that keeps staged rows invisible only to the point reads the
        overlay answers."""
        ing = self._ingest
        if ing is None:
            return False
        return ing.flush("barrier")

    # --- local ops: one send per batch (crdt.dart:39-54) ---

    def _write_sharding(self):
        """NamedSharding pinned onto write-scatter outputs, or None.
        The sharded model returns its key-axis sharding so local
        writes land laid out — no post-write re-shard copy."""
        return None

    def _refuse_in_pipeline(self, op: str) -> None:
        if self._pipe is not None:
            raise RuntimeError(
                f"{op} needs the host clock; it cannot run inside a "
                "pipelined() merge window — exit the window first")

    def _check_slots(self, slots: np.ndarray) -> None:
        # JAX scatter drops out-of-bounds indices silently; fail loudly
        # instead of losing writes.
        if slots.size and (slots.min() < 0 or slots.max() >= self.n_slots):
            raise IndexError(
                f"slot indices must be within [0, {self.n_slots}); got "
                f"range [{slots.min()}, {slots.max()}]")

    def _donate_writes(self) -> bool:
        """Donate old store buffers to write scatters only when (a) the
        backend honors donation (CPU ignores it with a warning) and
        (b) the current store snapshot has never been handed out via
        the public ``store`` property — a caller-held snapshot must
        stay readable, so an escaped store is never donated."""
        if self._store_escaped:
            return False
        try:
            return next(iter(self._store.lt.devices())).platform != "cpu"
        except Exception:
            return False

    def put_batch(self, slots, values, tombs=None) -> None:
        """Write values at slot indices; the whole batch shares ONE
        freshly-sent HLC (putAll semantics, crdt.dart:46-54).
        ``tombs`` (bool per entry) tombstones those entries under the
        same batch stamp — the mixed putAll shape (delete = put None,
        crdt.dart:58) that `delete_batch` alone can't express without
        spending a second stamp."""
        self._refuse_in_pipeline("put_batch")
        slots = np.asarray(slots, np.int32)
        self._check_slots(slots)
        self._check_value_width(values)
        if self._ingest is not None:
            # Validation above ran eagerly (staging must fail at the
            # call site, like the unbatched path); the rows themselves
            # wait for the flush stamp + fused commit.
            self._ingest.stage(
                slots.astype(np.int64),
                np.ascontiguousarray(np.broadcast_to(
                    np.asarray(values, np.int64), slots.shape)),
                None if tombs is None else np.ascontiguousarray(
                    np.broadcast_to(np.asarray(tombs, bool),
                                    slots.shape)))
            return
        slots = jnp.asarray(slots)
        values = jnp.asarray(values, jnp.int64)
        tombs_h = None if tombs is None else np.asarray(tombs, bool)
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())
        t = jnp.int64(self._canonical_time.logical_time)
        me = jnp.int32(self._table.ordinal(self._node_id))
        # One fused jit (not 7 eager scatters); donate the old lanes on
        # backends that support it so an O(k) write never copies the
        # O(n_slots) store.
        self._store = put_scatter(
            self._store, slots, values,
            t, me, tombs=None if tombs_h is None else jnp.asarray(tombs_h),
            donate=self._donate_writes(), sharding=self._write_sharding())
        self._store_escaped = False
        self.stats.puts += 1
        self.stats.records_put += int(slots.shape[0])
        self._emit_put(slots, values, tombs_h)

    def delete_batch(self, slots) -> None:
        """Tombstone slots (delete = put None, crdt.dart:58)."""
        self._refuse_in_pipeline("delete_batch")
        slots = np.asarray(slots, np.int32)
        self._check_slots(slots)
        if self._ingest is not None:
            self._ingest.stage(slots.astype(np.int64),
                               np.zeros(slots.shape[0], np.int64),
                               np.ones(slots.shape[0], bool))
            return
        slots = jnp.asarray(slots)
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())
        t = jnp.int64(self._canonical_time.logical_time)
        me = jnp.int32(self._table.ordinal(self._node_id))
        self._store = delete_scatter(self._store, slots, t, me,
                                     donate=self._donate_writes(),
                                     sharding=self._write_sharding())
        self._store_escaped = False
        self.stats.puts += 1
        self.stats.records_put += int(slots.shape[0])
        self._emit_delete(slots)

    # --- views (tombstones excluded, crdt.dart:16-29) ---

    @property
    def live_mask(self) -> jax.Array:
        self.drain_ingest()
        return self._store.occupied & ~self._store.tomb

    @property
    def values(self) -> jax.Array:
        """int64[n_slots]; only positions with ``live_mask`` are live.
        Hands out the live lane, so (like ``store``) it marks the
        snapshot escaped — later writes won't donate its buffer."""
        self.drain_ingest()
        self._store_escaped = True
        return self._store.val

    def _check_slot(self, slot: int) -> None:
        # JAX clamps out-of-range reads to the edge instead of raising,
        # which would answer confidently for the wrong slot.
        if not 0 <= slot < self.n_slots:
            raise IndexError(
                f"slot {slot} out of range [0, {self.n_slots})")

    def get(self, slot: int) -> Optional[int]:
        self._check_slot(slot)
        if self._ingest is not None:
            # Read-your-writes overlay: a staged row answers from host
            # memory — the later flush stamp beats anything the store
            # holds for the slot, so this IS the post-commit answer.
            staged, v = self._ingest.pending_value(slot)
            if staged:
                return v
        # One batched fetch: three sequential scalar reads pay three
        # full round trips on remote-proxied backends.
        occ, tomb, val = jax.device_get(
            (self._store.occupied[slot], self._store.tomb[slot],
             self._store.val[slot]))
        return int(val) if bool(occ) and not bool(tomb) else None

    def get_slot_record(self, slot: int) -> Optional[Record]:
        """Single-slot `Record` fetch (getRecord semantics,
        crdt.dart:146) — ONE batched device→host transfer of seven
        scalars, never a full-store readback (`record_map` is the
        bulk shape; a 1M-slot replica must answer a point read in
        O(1))."""
        self._check_slot(slot)
        # Records carry stamps, which staged rows only get at flush —
        # drain rather than synthesize an overlay answer.
        self.drain_ingest()
        occ, lt, node, val, mod_lt, mod_node, tomb = jax.device_get(
            (self._store.occupied[slot], self._store.lt[slot],
             self._store.node[slot], self._store.val[slot],
             self._store.mod_lt[slot], self._store.mod_node[slot],
             self._store.tomb[slot]))
        if not bool(occ):
            return None
        from ..hlc import MAX_COUNTER, SHIFT
        ids = self._table.ids()
        lt, mod_lt = int(lt), int(mod_lt)
        return Record(
            Hlc._raw(lt >> SHIFT, lt & MAX_COUNTER, ids[int(node)]),
            None if bool(tomb) else int(val),
            Hlc._raw(mod_lt >> SHIFT, mod_lt & MAX_COUNTER,
                     ids[int(mod_node)]))

    def contains_slot(self, slot: int) -> bool:
        """True if the slot holds a record, live OR tombstoned
        (containsKey semantics, crdt.dart:141)."""
        self._check_slot(slot)
        if self._ingest is not None \
                and self._ingest.pending_value(slot)[0]:
            return True
        return bool(self._store.occupied[slot])

    def is_deleted(self, slot: int) -> Optional[bool]:
        """None for never-written slots, else the tombstone flag
        (crdt.dart:61-64)."""
        self._check_slot(slot)
        if self._ingest is not None:
            staged, v = self._ingest.pending_value(slot)
            if staged:
                return v is None
        if not bool(self._store.occupied[slot]):
            return None
        return bool(self._store.tomb[slot])

    def clear(self, purge: bool = False) -> None:
        """Tombstone every LIVE slot with one batch HLC, or physically
        purge (crdt.dart:67-73: clear = putAll(None for live keys))."""
        if purge:
            return self.purge()
        slots = np.nonzero(np.asarray(self.live_mask))[0]
        if slots.size:            # empty putAll never touches the clock
            self.delete_batch(slots)

    def purge(self) -> None:
        """Physically drop all records (crdt.dart:168-169). The
        canonical clock and node table are untouched."""
        self.drain_ingest()
        self._store = empty_dense_store(self.n_slots)

    # --- tombstone epoch GC + online compaction (docs/STORAGE.md) ---

    def gc_purge(self, stability: Hlc, *,
                 drift_slack_ms: Optional[int] = None) -> int:
        """Epoch tombstone GC: physically drop every tombstone whose
        delete stamp every peer's durable watermark has passed —
        ``stability`` MUST be a fleet stability watermark
        (`GossipNode.stability_hlc` / `ServeTier.stability_hlc`; the
        crdtlint ``purge-watermark-unfenced`` rule holds library call
        sites to that). One donated device dispatch masks the purged
        rows out of all lanes (`ops.dense.gc_purge`); an unadvanced
        watermark short-circuits BEFORE dispatch, so idle GC passes
        cost nothing (ledger-asserted in the tests).

        The purge floor is the watermark minus a clock-drift slack
        (``hlc.MAX_DRIFT`` unless overridden — single-node callers
        whose watermark IS their own head pass 0): with the slack, any
        row a peer legitimately holds undelivered sits ABOVE the
        floor, which is what makes the merge-side resurrection fence
        precise — inbound rows below the floor targeting a PURGED
        slot are provably-dominated replays and are dropped (slots
        never purged here are untouched: an old row arriving at one
        for the first time — a migration stream, an initial sync — is
        new information, not a replay). Returns the number of slots
        purged."""
        self._refuse_in_pipeline("gc_purge")
        self.drain_ingest()
        from ..hlc import MAX_DRIFT, SHIFT
        slack = MAX_DRIFT if drift_slack_ms is None else int(drift_slack_ms)
        if slack < 0:
            raise ValueError(f"drift_slack_ms must be >= 0, got {slack}")
        floor = int(stability.logical_time) - (slack << SHIFT)
        if floor <= 0 or floor <= self._last_gc_floor_lt:
            return 0  # watermark hasn't advanced: zero dispatches
        from ..obs.registry import default_registry
        from ..ops.dense import gc_purge as _gc_purge_op
        new_store, purged_count, purged_mask = _gc_purge_op(
            self._store, jnp.int64(floor),
            donate=self._donate_writes(),
            sharding=self._write_sharding())
        mask_h = None
        if self._sem is not None or _sanitizer.enabled():
            n_purged, mask_h = jax.device_get((purged_count, purged_mask))
            mask_h = np.asarray(mask_h)
        else:
            n_purged = jax.device_get(purged_count)
        n_purged = int(n_purged)
        self._store = self._postprocess_store(new_store)
        self._store_escaped = False
        self._last_gc_floor_lt = floor
        self._gc_floor_lt = max(self._gc_floor_lt, floor)
        # Accumulate the device fence mask the merge paths consult —
        # purged slots only, so the fence can never eat first-time
        # deliveries (migration, initial sync) to slots it never GC'd.
        if self._gc_fence_dev is None:
            self._gc_fence_dev = purged_mask
        else:
            self._gc_fence_dev = jnp.logical_or(
                self._gc_fence_dev, purged_mask)
        if n_purged and self._sem is not None:
            typed_purged = mask_h & (self._sem != 0)
            if typed_purged.any():
                # Purged slots revert to the LWW default — the typed
                # tag described the tombstoned record, which is gone.
                sem = self._sem.copy()
                sem[typed_purged] = 0
                self._sem = sem if sem.any() else None
                self._sem_dev = None
                self._sem_version += 1
        if _sanitizer.enabled():
            # Arm the post-purge resurrection check: every later merge
            # asserts no recorded slot re-occupies below the floor
            # (sanitizer.check_dense_no_resurrection). Compaction
            # remaps slots, so it retires the record.
            slots = np.nonzero(mask_h)[0]
            if self._gc_purged is not None:
                prev_slots, _ = self._gc_purged
                slots = np.union1d(prev_slots, slots)
            self._gc_purged = (slots, floor)
        default_registry().counter(
            "crdt_tpu_gc_purged_slots_total",
            "tombstoned slots physically reclaimed by epoch GC").inc(
                n_purged, node=str(self._node_id))
        default_registry().counter(
            "crdt_tpu_gc_passes_total",
            "gc_purge dispatches (watermark advanced)").inc(
                node=str(self._node_id))
        return n_purged

    def compact(self, ranges=None) -> np.ndarray:
        """Online store compaction: remap surviving rows to a dense
        prefix (per span — the default spans the whole store) and
        rebuild the digest-tree levels, all in ONE donated device
        dispatch (`ops.dense.compact_remap`). Returns the slot
        translation table ``int32[n_slots]`` — ``translation[old] =
        new`` for occupied rows, ``-1`` for empty slots — which the
        caller MUST apply to every external slot reference
        (`KeyedDenseCrdt.compact` rewrites its intern map; raw-slot
        callers compact only when they own the slot space,
        docs/STORAGE.md). ``ranges`` restricts compaction to half-open
        ``(lo, hi)`` spans; rows outside keep their slots, so routing
        arcs stay range-preserving. The digest cache is re-seeded from
        the in-program rebuild, so the next anti-entropy walk costs
        zero digest dispatches."""
        self._refuse_in_pipeline("compact")
        self.drain_ingest()
        spans = self._normalize_ranges(
            ((0, self.n_slots),) if ranges is None else ranges)
        k = max(1, len(spans))
        pad = 1
        while pad < k:
            pad *= 2
        los = np.zeros(pad, np.int64)
        his = np.zeros(pad, np.int64)
        for i, (lo, hi) in enumerate(spans):
            los[i] = lo
            his[i] = hi
        from ..ops.dense import compact_remap
        from ..ops.digest import build_digest_tree
        sem_dev = self._sem_device() if self._sem is not None else None
        out = compact_remap(self._store, jnp.asarray(los),
                            jnp.asarray(his), sem_dev,
                            leaf_width=self.DIGEST_LEAF_WIDTH,
                            donate=self._donate_writes(),
                            sharding=self._write_sharding())
        if sem_dev is not None:
            new_store, new_sem, translation, _live, levels = out
        else:
            new_store, translation, _live, levels = out
            new_sem = None
        translation = np.asarray(jax.device_get(translation))
        self._store = self._postprocess_store(new_store)
        self._store_escaped = False
        if new_sem is not None:
            sem_h = np.asarray(jax.device_get(new_sem)).astype(np.int8)
            self._sem = sem_h if sem_h.any() else None
            self._sem_dev = None
            self._sem_version += 1
        # Recorded purge slots predate the remap; retire the record
        # and the device fence mask rather than translate them
        # (purged slots are unoccupied, so their translations are -1
        # anyway, and post-compact slot identity belongs to the
        # single remap owner — docs/STORAGE.md).
        self._gc_purged = None
        self._gc_fence_dev = None
        # Seed AFTER the store swap (the setter cleared the cache) and
        # the sem version bump, under the exact key the next
        # `digest_tree` lookup builds.
        tree = build_digest_tree(self.n_slots, self.DIGEST_LEAF_WIDTH,
                                 levels)
        self._digest_cache = (self._digest_key(), tree)
        from ..obs.registry import default_registry
        default_registry().counter(
            "crdt_tpu_compact_passes_total",
            "compact_remap dispatches").inc(node=str(self._node_id))
        return translation

    def grow(self, n_slots: int) -> None:
        """Grow the slot capacity to ``n_slots`` (records keep their
        slots; new slots start empty). The dense analogue of the
        reference map's unbounded growth (map_crdt.dart:10) — capacity
        is a layout choice, not a data bound. Shrinking would drop
        records; it is refused.

        Peers at the old capacity keep syncing with this replica
        (their narrower changesets are padded on ingest); merging THIS
        replica's wider changesets into an ungrown peer raises there
        until the peer grows too. With ``executor="auto"`` the Mosaic
        kernel path engages/disengages with tile alignment
        (`crdt_tpu.ops.TILE`); a forced ``executor="pallas"`` refuses
        an unaligned growth outright."""
        if n_slots < self.n_slots:
            raise ValueError(
                f"cannot shrink {self.n_slots} -> {n_slots} slots "
                "(records would be dropped); build a new replica and "
                "merge instead")
        if self._executor in ("pallas", "pallas-interpret"):
            from ..ops.pallas_merge import TILE
            if n_slots % TILE:
                raise ValueError(
                    f"executor={self._executor!r} needs n_slots % "
                    f"{TILE} == 0; got {n_slots}")
        if n_slots == self.n_slots:
            return
        self.drain_ingest()
        if self._sem is not None:
            # New slots start as LWW (tag 0) — the untyped default.
            self._sem = np.concatenate(
                [self._sem, np.zeros(n_slots - self.n_slots, np.int8)])
            self._sem_dev = None
        if self._gc_fence_dev is not None:
            # New slots were never purged — the fence must not cover
            # them (first-time deliveries land there).
            self._gc_fence_dev = jnp.concatenate(
                [self._gc_fence_dev,
                 jnp.zeros(n_slots - self.n_slots, jnp.bool_)])
        pad = empty_dense_store(n_slots - self.n_slots)
        self._store = DenseStore(*(
            jnp.concatenate([lane, pad_lane])
            for lane, pad_lane in zip(self._store, pad)))

    def __len__(self) -> int:
        return int(jnp.sum(self.live_mask))

    # --- per-slot semantics (crdt_tpu.semantics, docs/TYPES.md) ---

    @property
    def _has_typed(self) -> bool:
        """Any slot carrying a non-LWW tag? (`_sem` collapses back to
        None when a migration returns every slot to LWW, so this is a
        plain None check — the hot paths branch on it.)"""
        return self._sem is not None

    def _sem_host(self) -> np.ndarray:
        """The per-slot tag column as host int8 (all zeros when the
        replica is untyped). Do not mutate — go through
        `set_semantics`, which versions the column."""
        if self._sem is None:
            return np.zeros(self.n_slots, np.int8)
        return self._sem

    def _sem_device(self) -> jax.Array:
        """Device mirror of the tag column, rebuilt lazily after each
        migration/grow (the typed kernels take it as a plain operand,
        so jit caches stay warm across migrations)."""
        if self._sem_dev is None:
            self._sem_dev = jnp.asarray(self._sem_host())
        return self._sem_dev

    def set_semantics(self, slots, semantics) -> None:
        """Assign a registered semantics (`crdt_tpu.semantics`) to
        slots — by spec, name, or tag. Typed slots join through the
        per-tag sub-semilattice (`semantics.kernels`) instead of the
        LWW winner-takes-all rule; clock lanes, watermarks and guards
        are unchanged (the semidirect-product composition).

        This is replica-local CONFIGURATION, not replicated state:
        every peer must run the same migration before syncing typed
        slots (the packed wire form carries tags and rejects
        mismatches; docs/TYPES.md has the rollout recipe). Migrating a
        slot does not rewrite its lane — migrate before first write."""
        self._refuse_in_pipeline("set_semantics")
        self.drain_ingest()
        from ..semantics import SemanticsSpec, by_tag, get_semantics
        if isinstance(semantics, SemanticsSpec):
            spec = semantics
        elif isinstance(semantics, str):
            spec = get_semantics(semantics)
        else:
            spec = by_tag(int(semantics))
        if spec.tag != 0:
            if self._value_width != 64:
                raise ValueError(
                    "typed semantics pack state into the full int64 "
                    "value lane; this replica was built with "
                    "value_width=32")
            if self._executor in ("pallas", "pallas-interpret"):
                raise ValueError(
                    f"typed semantics run on the XLA path; "
                    f"executor={self._executor!r} forces the Mosaic "
                    "kernel (use executor='auto' or 'xla')")
        slots = np.asarray(slots, np.int32).reshape(-1)
        self._check_slots(slots)
        sem = (self._sem if self._sem is not None
               else np.zeros(self.n_slots, np.int8))
        sem[slots] = np.int8(spec.tag)
        self._sem = sem if sem.any() else None
        self._sem_dev = None
        self._sem_version += 1
        # Cached packs may hold rows under the old tags (or withhold
        # rows that are now LWW) — the version key alone would let an
        # in-flight entry at the same watermark survive. Digests mix
        # the tag lane, so the cached tree goes with them.
        self._pack_cache.clear()
        self._digest_cache = None

    def semantics_of(self, slot: int):
        """The registered `SemanticsSpec` governing a slot."""
        self._check_slot(slot)
        from ..semantics import by_tag
        return by_tag(0 if self._sem is None else int(self._sem[slot]))

    def _lane_value(self, slot: int) -> int:
        """Raw int64 lane at a slot, ingest-overlay aware — what a
        typed read-modify-write builds on. Tombstones do NOT zero
        typed lanes (deletion is the LWW action layered on top, and
        un-deleting reveals the converged state), so this reads the
        lane itself, not the live view."""
        if self._ingest is not None:
            staged, v = self._ingest.pending_value(slot)
            if staged:
                return 0 if v is None else int(v)
        occ, val = jax.device_get(
            (self._store.occupied[slot], self._store.val[slot]))
        return int(val) if bool(occ) else 0

    def _typed_spec(self, slot: int, *names):
        self._check_slot(slot)
        spec = self.semantics_of(slot)
        if spec.name not in names:
            raise TypeError(
                f"slot {slot} holds {spec.name!r} semantics; this op "
                f"needs {' / '.join(names)} (set_semantics first)")
        return spec

    def counter_add(self, slot: int, delta: int) -> int:
        """Add ``delta`` to a counter slot and return the new decoded
        value. ``gcounter`` slots refuse negative deltas; ``pncounter``
        slots credit the pos/neg half. Works inside ``ingest()``
        windows (the staged overlay makes consecutive adds
        accumulate). The dense counter contract: ONE writer per slot —
        the merge join is per-lane max, so concurrent writers on one
        slot lose increments; give each replica its own slot and sum
        (docs/TYPES.md, examples/counter_example.py)."""
        spec = self._typed_spec(slot, "gcounter", "pncounter")
        delta = int(delta)
        lane = self._lane_value(slot)
        if spec.name == "gcounter":
            if delta < 0:
                raise ValueError(
                    "gcounter is grow-only; use pncounter semantics "
                    "for decrements")
            lane = lane + delta
            if lane >= 1 << 63:
                raise OverflowError("gcounter lane overflow")
        else:
            from ..semantics.kernels import _PN_HALF
            pos = (lane >> 32) & _PN_HALF
            neg = lane & _PN_HALF
            if delta >= 0:
                pos += delta
            else:
                neg -= delta
            if pos > _PN_HALF or neg > _PN_HALF:
                raise OverflowError(
                    "pncounter half overflow (31 bits per direction)")
            lane = (pos << 32) | neg
        self.put_batch([slot], [lane])
        return int(spec.decode(lane))

    def counter_value(self, slot: int) -> int:
        """Decoded counter value at a slot (pos − neg for pncounter)."""
        spec = self._typed_spec(slot, "gcounter", "pncounter")
        return int(spec.decode(self._lane_value(slot)))

    def orset_add(self, slot: int, element: int) -> frozenset:
        """Add an element (``[0, ORSET_UNIVERSE)``) to an OR-set slot:
        bump its causal length even→odd. Adding a present element is a
        no-op (no new write, no clock tick). Returns the updated
        membership."""
        spec = self._typed_spec(slot, "orset")
        from ..semantics import ORSET_MAX_LEN, ORSET_UNIVERSE
        e = int(element)
        if not 0 <= e < ORSET_UNIVERSE:
            raise ValueError(
                f"orset element out of universe [0, {ORSET_UNIVERSE}): "
                f"{e}")
        lane = self._lane_value(slot)
        n = (lane >> (4 * e)) & 0xF
        if n % 2 == 1:
            return spec.decode(lane)
        if n >= ORSET_MAX_LEN:
            raise OverflowError(
                f"orset causal length saturated at {ORSET_MAX_LEN} "
                f"for element {e} (no further add/remove cycles)")
        lane = (lane & ~(0xF << (4 * e))) | ((n + 1) << (4 * e))
        self.put_batch([slot], [lane])
        return spec.decode(lane)

    def orset_remove(self, slot: int, element: int) -> frozenset:
        """Remove an element: bump its causal length odd→even.
        Removing an absent element is a no-op. Returns the updated
        membership."""
        spec = self._typed_spec(slot, "orset")
        from ..semantics import ORSET_MAX_LEN, ORSET_UNIVERSE
        e = int(element)
        if not 0 <= e < ORSET_UNIVERSE:
            raise ValueError(
                f"orset element out of universe [0, {ORSET_UNIVERSE}): "
                f"{e}")
        lane = self._lane_value(slot)
        n = (lane >> (4 * e)) & 0xF
        if n % 2 == 0:
            return spec.decode(lane)
        if n >= ORSET_MAX_LEN:
            raise OverflowError(
                f"orset causal length saturated at {ORSET_MAX_LEN} "
                f"for element {e} (no further add/remove cycles)")
        lane = (lane & ~(0xF << (4 * e))) | ((n + 1) << (4 * e))
        self.put_batch([slot], [lane])
        return spec.decode(lane)

    def orset_members(self, slot: int) -> frozenset:
        """Current members of an OR-set slot (odd causal lengths)."""
        spec = self._typed_spec(slot, "orset")
        return spec.decode(self._lane_value(slot))

    def mvreg_put(self, slot: int, value: int) -> None:
        """Write a multi-value register: this write's fresh HLC is
        strictly newer than anything the replica has seen, so it
        replaces local values outright; CONCURRENT peer writes (equal
        lt under different nodes) union on merge up to the top
        ``MVREG_K``."""
        spec = self._typed_spec(slot, "mvreg")
        self.put_batch([slot], [spec.encode(value)])

    def mvreg_get(self, slot: int) -> Tuple[int, ...]:
        """Concurrent values at an mvreg slot, largest first — one
        element after any local write, possibly several after merging
        concurrent peers."""
        spec = self._typed_spec(slot, "mvreg")
        return spec.decode(self._lane_value(slot))

    # --- watch/reactivity (C13, crdt.dart:162-164) ---

    def watch(self, slot: Optional[int] = None) -> ChangeStream:
        """Per-slot or whole-store change stream. Events are
        ``(slot, value)`` with value ``None`` for deletes, emitted
        host-side after device writes land (reactivity never lives in
        the kernel — SURVEY.md §7 hard part 6)."""
        return self._hub.stream(slot)

    def _watch_decode(self, slot, value):
        """Decode one committed lane value for a watch event: typed
        slots (counter/orset/mvreg) must emit what their reads return
        — `spec.decode(lane)` — never the packed raw lane a subscriber
        cannot interpret. Untyped replicas pay a single None check."""
        if value is None or self._sem is None:
            return value
        tag = int(self._sem[slot])
        if tag == 0:
            return value
        from ..semantics import by_tag
        return by_tag(tag).decode(int(value))

    def _emit_put(self, slots, values, tombs=None) -> None:
        if not self._hub.active:
            return  # no subscribers: bulk path stays device-only
        # Host copies ONCE per batch — the arrays arrive as device
        # buffers here, and a per-lookup np.asarray would re-transfer
        # the whole lane to read one element.
        slot_arr = np.asarray(slots)
        val_arr = np.asarray(values)

        def pairs():
            sl = [int(x) for x in slot_arr]
            vals = [None if (tombs is not None and bool(tombs[i]))
                    else self._watch_decode(sl[i], int(val_arr[i]))
                    for i in range(len(slot_arr))]
            return sl, vals

        def get(k):
            if not isinstance(k, (int, np.integer)):
                return False, None
            hit = np.nonzero(slot_arr == k)[0]
            if hit.size == 0:
                return False, None
            i = int(hit[-1])
            deleted = tombs is not None and bool(tombs[i])
            return True, (None if deleted
                          else self._watch_decode(int(k),
                                                  int(val_arr[i])))

        # A raw slot array may repeat a slot; keyed streams must then
        # see every occurrence (add_batch's per-pair contract), so the
        # O(1) keyed shortcut only applies to duplicate-free batches.
        unique = len(np.unique(slot_arr)) == len(slot_arr)
        self._hub.add_batch(pairs, get if unique else None)

    def _emit_delete(self, slots) -> None:
        if not self._hub.active:
            return
        slot_arr = np.asarray(slots)
        unique = len(np.unique(slot_arr)) == len(slot_arr)
        self._hub.add_batch(
            lambda: ([int(s) for s in slot_arr],
                     [None] * len(slot_arr)),
            (lambda k: (isinstance(k, (int, np.integer))
                        and bool(np.any(slot_arr == k)), None))
            if unique else None)

    def _emit_merge_wins(self, store: DenseStore, win) -> None:
        """Winner change events from the fan-in's win mask — batched,
        post-dispatch (the device work is already queued); a subscriber
        costs one win-mask readback, never a per-record device loop."""
        if not self._hub.active:
            return
        win, tomb, val = jax.device_get((win, store.tomb, store.val))
        widx = np.nonzero(win)[0]

        def pairs():
            return ([int(s) for s in widx],
                    [None if tomb[s]
                     else self._watch_decode(int(s), int(val[s]))
                     for s in widx])

        def get(k):
            if not (isinstance(k, (int, np.integer))
                    and 0 <= k < win.shape[0] and win[k]):
                return False, None
            return True, (None if tomb[k]
                          else self._watch_decode(int(k), int(val[k])))

        # crdtlint: disable=add-batch-unique-keys -- widx comes from np.nonzero(win): a slot mask cannot repeat a slot, so the batch is unique by construction
        self._hub.add_batch(pairs, get)

    # --- wire interop (C10/C11): every replica speaks the JSON wire
    # format (crdt_json.dart:8-37; example/crdt_example.dart:12-16), so
    # a dense replica can sync with MapCrdt/TpuMapCrdt or external
    # JSON peers, not just other dense stores. ---

    def _check_value_width(self, values) -> None:
        if self._value_width == 32:
            v = np.asarray(values, np.int64)
            if v.size and (v.min() < -(2 ** 31) or v.max() >= 2 ** 31):
                raise ValueError(
                    "value_width=32 replica got a value outside int32 "
                    "range; use value_width=64 or store a payload-"
                    "table index instead")

    def _check_int_values(self, record_map: Dict[int, Record]) -> None:
        """The payload lane is int64; any other type would be silently
        truncated and (sharing the peer's hlc) diverge forever — fail
        loudly, identically on every record ingest path."""
        for slot, rec in record_map.items():
            if rec.value is not None and (
                    isinstance(rec.value, bool)
                    or not isinstance(rec.value, (int, np.integer))):
                # bool is an int subclass but would be stored as 0/1
                # and re-exported as such under the peer's hlc — the
                # silent-divergence shape this check exists to stop.
                raise TypeError(
                    f"DenseCrdt values must be ints; slot {slot} got "
                    f"{type(rec.value).__name__}")

    def put_slot_records(self, record_map: Dict[int, Record]) -> None:
        """Raw record writes preserving each record's own ``hlc`` and
        ``modified`` stamps — the putRecords storage primitive
        (crdt.dart:151-155): records land verbatim, with NO LWW compare
        and NO canonical-clock involvement (put_record's contract).
        Values must be ints (or None tombstones). Bulk-import shape:
        restoring a record dump, seeding a replica, or backing the
        `Crdt` storage slots through `KeyedDenseCrdt`."""
        if not record_map:
            return
        # Verbatim stamps must not interleave with a pending flush's
        # send_batch stamps — barrier before the raw scatter.
        self.drain_ingest()
        k = len(record_map)
        slots = np.fromiter(record_map.keys(), np.int64, count=k)
        self._check_slots(slots)
        recs = list(record_map.values())
        self._check_int_values(record_map)
        self._check_value_width(
            [0 if r.value is None else int(r.value) for r in recs])
        self._intern_ids({r.hlc.node_id for r in recs}
                         | {r.modified.node_id for r in recs})
        ords = {nid: i for i, nid in enumerate(self._table.ids())}
        # Pad k to a power of two (invalid rows scatter to the
        # n_slots sentinel, mode="drop") so the jitted scatter compiles
        # O(log k) distinct shapes — same trick as merge_records.
        padded = 1 << max(k - 1, 1).bit_length()
        slot_arr = np.full((padded,), self.n_slots, np.int64)
        lt = np.zeros((padded,), np.int64)
        node = np.zeros((padded,), np.int32)
        val = np.zeros((padded,), np.int64)
        mod_lt = np.zeros((padded,), np.int64)
        mod_node = np.zeros((padded,), np.int32)
        tomb = np.zeros((padded,), bool)
        slot_arr[:k] = slots
        lt[:k] = [r.hlc.logical_time for r in recs]
        node[:k] = [ords[r.hlc.node_id] for r in recs]
        val[:k] = [0 if r.value is None else int(r.value) for r in recs]
        mod_lt[:k] = [r.modified.logical_time for r in recs]
        mod_node[:k] = [ords[r.modified.node_id] for r in recs]
        tomb[:k] = [r.is_deleted for r in recs]
        from ..ops.dense import record_scatter
        self._store = self._postprocess_store(record_scatter(
            self._store, jnp.asarray(slot_arr), jnp.asarray(lt),
            jnp.asarray(node), jnp.asarray(val), jnp.asarray(mod_lt),
            jnp.asarray(mod_node), jnp.asarray(tomb),
            donate=self._donate_writes(),
            sharding=self._write_sharding()))
        self._store_escaped = False
        self.stats.puts += 1
        self.stats.records_put += k
        if self._hub.active:
            for slot, rec in record_map.items():
                self._hub.add(int(slot),
                              None if rec.is_deleted
                              else self._watch_decode(int(slot),
                                                      int(rec.value)))

    def _delta_mask(self, modified_since: Optional[Hlc]) -> np.ndarray:
        if modified_since is None:
            mask = self._store.occupied
        else:
            mask = dense_delta_mask(
                self._store, jnp.int64(modified_since.logical_time))
        return mask

    def count_modified_since(self, modified_since: Optional[Hlc] = None
                             ) -> int:
        """Delta-backlog size for lag monitoring: occupied slots with
        ``mod_lt >= modified_since`` (tombstones included). One masked
        sum on device, one scalar fetch — never materializes records.

        Inside an ingest window, staged rows count too (their flush
        stamp is at-or-after the canonical head, so they are modified
        under any watermark bound) — lag monitors see the backlog
        without forcing a flush."""
        mask = self._delta_mask(modified_since)
        ing = self._ingest
        if ing is not None and ing.pending_rows:
            mask = mask.at[jnp.asarray(
                ing.pending_slot_array())].set(True)
        return int(jax.device_get(jnp.sum(mask)))

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[int, Record]:
        """Slot→Record export (recordMap semantics, crdt.dart:140-169,
        inclusive ``modified_since`` bound) — the bridge between the
        columnar lanes and the record-dict/JSON world. One device→host
        transfer; decode is vectorized (numpy unpack + object-array
        node gather), with per-record work reduced to the raw
        ``Hlc``/``Record`` allocations."""
        self.drain_ingest()
        mask = self._delta_mask(modified_since)
        # One batched fetch (async prefetch per leaf) instead of seven
        # sequential device->host round trips.
        mask, lt, node, val, mod_lt, mod_node, tomb = jax.device_get(
            (mask, self._store.lt, self._store.node, self._store.val,
             self._store.mod_lt, self._store.mod_node, self._store.tomb))
        idx = np.nonzero(mask)[0]
        if idx.size == 0:
            return {}
        ids = np.array(self._table.ids(), object)
        from ..hlc import MAX_COUNTER, SHIFT
        cols = (idx.tolist(),
                (lt[idx] >> SHIFT).tolist(),
                (lt[idx] & MAX_COUNTER).tolist(),
                ids[node[idx]],
                val[idx].tolist(), tomb[idx].tolist(),
                (mod_lt[idx] >> SHIFT).tolist(),
                (mod_lt[idx] & MAX_COUNTER).tolist(),
                ids[mod_node[idx]])
        raw = Hlc._raw
        return {
            slot: Record(raw(ms, c, n), None if tb else v,
                         raw(mms, mc, mn))
            for slot, ms, c, n, v, tb, mms, mc, mn in zip(*cols)
        }

    def to_json(self, modified_since: Optional[Hlc] = None,
                key_encoder: Optional[KeyEncoder] = None,
                value_encoder: Optional[ValueEncoder] = None) -> str:
        """Wire JSON export (crdt.dart:124-135): slots stringify as int
        keys, matching the reference's int-key golden format.

        With default coders this streams straight from the lanes —
        numpy unpack, C-codec batch HLC formatting, direct string
        assembly (every piece is JSON-plain: int keys, int/null
        values) — byte-identical to the generic encoder but without
        materializing a Record dict (a 1M-slot export runs in seconds,
        benchmarks/suite.py `dense_to_json`)."""
        self.drain_ingest()
        if key_encoder is None and value_encoder is None:
            fast = self._to_json_fast(modified_since)
            if fast is not None:
                return fast
        return crdt_json.encode(self.record_map(modified_since),
                                key_encoder=key_encoder,
                                value_encoder=value_encoder)

    def _to_json_fast(self, modified_since: Optional[Hlc]) -> Optional[str]:
        """Lane-direct wire export, or None to defer to the generic
        path (no native codec; an out-of-range year; a node id that is
        not UTF-8 encodable). Escape-needing node ids are handled by
        the C assembler's JSON escaping."""
        from .. import native
        codec = native.load()
        if codec is None:
            return None
        id_strs = [str(n) for n in self._table.ids()]
        mask = self._delta_mask(modified_since)
        # `modified` is local-only and never serialized
        # (record.dart:28-31) — the wire fetch skips those lanes.
        mask, lt, node, val, tomb = jax.device_get(
            (mask, self._store.lt, self._store.node, self._store.val,
             self._store.tomb))
        idx = np.nonzero(mask)[0]
        if idx.size == 0:
            return "{}"
        from ..hlc import MAX_COUNTER, SHIFT
        hlcs = codec.format_hlc_batch(
            (lt[idx] >> SHIFT).tolist(), (lt[idx] & MAX_COUNTER).tolist(),
            np.array(id_strs, object)[node[idx]].tolist())
        if None in hlcs:
            # deferred item: out-of-window year (generic path raises)
            # or a non-UTF-8 node id (generic path serializes it)
            return None
        # C one-pass assembly (int slot keys; escape-safe for any node
        # id). Values: int, or None for tombstones — all scalars, so
        # the dumps fallback never fires, but pass the real one anyway.
        values = [None if tb else v
                  for v, tb in zip(val[idx].tolist(), tomb[idx].tolist())]
        return codec.format_wire(idx.tolist(), hlcs, values,
                                 crdt_json.compact_dumps)

    def merge_records(self, record_map: Dict[int, Record]) -> None:
        """Fan-in a record dict (from a MapCrdt/TpuMapCrdt peer or a
        JSON decode). Values must be ints (or None tombstones) — the
        dense model's payload lane is int64.

        Clock absorption and recv guards run host-side, in the
        payload's own iteration order — the reference's visit order
        (crdt.dart:80-85) — through the shared vectorized fold
        (`utils.host_guards.recv_fold_columns`, the same one the other
        host backends use), so guard trips, their payloads, and the
        partially-advanced canonical on failure match ``MapCrdt.merge``
        exactly. A slot-ordered device-side check could disagree on
        which records the fast path shields (hlc.dart:85). After
        absorption the canonical clock is ≥ every remote lt, so the
        join itself needs no further guard work and is
        order-independent.

        Cost is O(k) in the delta size — host arrays, transfer, and
        the `sparse_fanin_step` gather/scatter are all k-wide (a
        10-record JSON sync into a 1M-slot replica must not
        materialize 1M-wide lanes). Equivalence with the full-width
        changeset join is property-tested
        (tests/test_dense_crdt.py::TestSparseWireDelta)."""
        self._refuse_in_pipeline("merge_records")  # host recv fold
        self.drain_ingest()
        if not record_map:
            self.merge_many([])
            return
        k = len(record_map)
        slots = np.fromiter(record_map.keys(), np.int64, count=k)
        recs = list(record_map.values())
        from .. import native
        codec = native.load()
        if codec is not None:
            lt_buf, nodes, values = codec.records_to_columns(recs, False)
            lt = np.frombuffer(lt_buf, np.int64)
        else:
            lt = np.fromiter((r.hlc.logical_time for r in recs),
                             np.int64, count=k)
            nodes = [r.hlc.node_id for r in recs]
            values = [r.value for r in recs]
        self._merge_columns(slots, lt, nodes, values)

    def merge_json(self, json_str: str,
                   key_decoder: Optional[KeyDecoder] = None,
                   value_decoder: Optional[ValueDecoder] = None) -> None:
        """Columnar wire JSON ingest (crdt.dart:100-109): C batch HLC
        parse → packed int64 lanes → shared recv fold →
        `sparse_fanin_step`, no per-record Record/Hlc objects (the
        same decode shape `TpuMapCrdt`/`SqliteCrdt` ingest through).
        Keys decode to int slots by default."""
        self._refuse_in_pipeline("merge_json")  # host recv fold
        self.drain_ingest()
        # Tick parity with the generic Crdt.merge_json: the decode-time
        # `modified` stamp consumes one wall read there
        # (Crdt._decode_wall_millis contract) — a merge immediately
        # re-stamps winners, so only the READ must happen here.
        self._wall_clock()
        if (key_decoder is None or key_decoder is int) \
                and value_decoder is None:
            from .. import native
            codec = native.load()
            scanned = (codec.parse_wire_dense(json_str)
                       if codec is not None else None)
            if scanned is not None:
                # Zero-Python-object lane: the C scan produced raw
                # columnar buffers (no key strings, no value ints) —
                # validate ranges, map node ordinals, and join.
                sbuf, ltbuf, nibuf, uniq, vbuf, tbuf, vmin, vmax = scanned
                k = len(tbuf)
                if not k:
                    self.merge_many([])
                    return
                slots = np.frombuffer(sbuf, np.int32)
                lt = np.frombuffer(ltbuf, np.int64)
                ni = np.frombuffer(nibuf, np.int32)
                val = np.frombuffer(vbuf, np.int64)
                tomb = np.frombuffer(tbuf, np.uint8).astype(bool)
                keep = self._last_wins_keep(slots)
                if keep is not None:
                    # Duplicate literal wire keys: collapse last-wins
                    # (decode-dict parity) before anything counts or
                    # validates the dropped occurrences.
                    slots, lt, ni, val, tomb = (
                        slots[keep], lt[keep], ni[keep], val[keep],
                        tomb[keep])
                    k = len(slots)
                self.stats.merges += 1
                self.stats.add_seen_lazy(k)
                self._check_slots(slots)
                self._check_value_width(
                    np.array([vmin, vmax], np.int64)
                    if keep is None else val)
                self._intern_ids(uniq)
                node = self._table.encode(uniq)[ni]
                self._merge_validated(slots, lt, node, val, tomb)
                return
        keys, lt, nodes, values = crdt_json.decode_columns(
            json_str, key_decoder=key_decoder or int,
            value_decoder=value_decoder)
        if not keys:
            self.merge_many([])
            return
        self._merge_columns(np.asarray(keys, np.int64), lt, nodes,
                            values)

    @staticmethod
    def _last_wins_keep(slots: np.ndarray) -> Optional[np.ndarray]:
        """Indices keeping the LAST occurrence per duplicate slot (in
        payload order), or None when already unique. Distinct wire
        keys may decode to ONE slot ("5" and "05" under the int key
        decoder); the legacy decode-dict collapsed those last-wins
        BEFORE the merge ever saw them, and the scatter/wide joins
        require unique slots — XLA scatter with duplicate indices has
        backend-dependent winner order."""
        k = len(slots)
        # First occurrence in the reversed view = last in the payload.
        _, idx = np.unique(slots[::-1], return_index=True)
        if len(idx) == k:
            return None
        return np.sort(k - 1 - idx)

    def _merge_columns(self, slots: np.ndarray, lt: np.ndarray,
                       node_ids: List[Any], values: List[Any]) -> None:
        """The shared O(k) columnar merge core (`merge_records` /
        `merge_json`): ``lt`` is int64[k] packed logical times aligned
        with ``slots``/``node_ids``/``values``. Every validation runs
        BEFORE the first clock mutation (and before the absorption
        wall read — the legacy visit order under a counting clock), so
        a rejected payload leaves the replica untouched. Duplicate
        slots collapse last-wins first — dropped occurrences are never
        seen, validated, or counted, exactly like the decode dict."""
        keep = self._last_wins_keep(slots)
        if keep is not None:
            slots, lt = slots[keep], lt[keep]
            node_ids = [node_ids[i] for i in keep]
            values = [values[i] for i in keep]
        k = len(slots)
        self.stats.merges += 1
        # add_seen_lazy (host int here): `records_seen +=` would drain
        # any pending lazy device scalar with a blocking readback.
        self.stats.add_seen_lazy(k)
        self._check_slots(slots)
        # The payload lane is int64; any other type (incl. bool, an
        # int subclass that would store as 0/1) would silently diverge
        # under the peer's hlc — one O(k) offender scan, on this
        # non-C fallback path only (the C wire scan rejects upstream
        # by deferring, and record dicts are already Python-bound).
        from .. import native
        codec = native.load()
        if codec is not None:
            tomb = np.frombuffer(codec.none_mask(values), bool)
        else:
            tomb = np.fromiter((v is None for v in values), bool, count=k)
        bad = next((i for i, v in enumerate(values)
                    if v is not None
                    and (isinstance(v, bool)
                         or not isinstance(v, (int, np.integer)))), None)
        if bad is not None:
            raise TypeError(
                f"DenseCrdt values must be ints; slot {slots[bad]} got "
                f"{type(values[bad]).__name__}")
        val = np.fromiter((0 if v is None else v for v in values),
                          np.int64, count=k)
        self._check_value_width(val)
        self._intern_ids(set(node_ids))
        node = self._table.encode(node_ids)
        self._merge_validated(slots, lt, node, val, tomb)

    def _merge_validated(self, slots: np.ndarray, lt: np.ndarray,
                         node: np.ndarray, val: np.ndarray,
                         tomb: np.ndarray, sem_ok: bool = False,
                         repack_since_lt: Optional[int] = None
                         ) -> Optional[jax.Array]:
        """Columnar merge tail on fully validated int lanes: recv fold,
        store join, watch emission, final send bump. ``node`` already
        holds LOCAL ordinals; stats counters are the caller's job up to
        ``merges``/``records_seen`` (this adds adopted).

        ``sem_ok`` asserts the caller verified the payload's semantics
        tags against the local column (`merge_packed` with a ``sem``
        lane). Without it, rows landing on typed slots are WITHHELD —
        an LWW-framed wire (record dicts, JSON, pre-semantics packed
        frames) cannot prove it joins under the right lattice, and
        joining a counter lane by LWW would corrupt it. Withheld rows
        count in ``crdt_tpu_sync_semantics_downgrade_total``.

        ``repack_since_lt`` asks the join to ALSO emit the next pack's
        delta mask (``mod_lt >= since``) from the same fused program
        (`merge_and_repack`); returns that device mask when the sparse
        fused path ran, None otherwise (wide/typed/withheld-empty join,
        where the caller falls back to a separate `pack_since`)."""
        if not sem_ok and self._sem is not None:
            typed = self._sem[slots] != 0
            if typed.any():
                from ..obs.registry import default_registry
                default_registry().counter(
                    "crdt_tpu_sync_semantics_downgrade_total",
                    "typed rows withheld from LWW-only wire forms by "
                    "direction").inc(int(typed.sum()),
                                     direction="inbound",
                                     node=str(self._node_id))
                keep = ~typed
                slots, lt, node, val, tomb = (
                    slots[keep], lt[keep], node[keep], val[keep],
                    tomb[keep])
                if not len(slots):
                    # Same two clock ticks as an empty merge
                    # (absorption wall read + final send bump), so
                    # injected clocks stay in step with peers that
                    # shipped nothing.
                    self._wall_clock()
                    self._canonical_time = Hlc.send(
                        self._canonical_time,
                        millis=self._wall_clock())
                    return None
        floor = self._gc_floor_lt
        if floor and self._gc_fence_dev is not None and len(slots):
            # Resurrection fence (docs/STORAGE.md): a row below the GC
            # floor targeting a slot this replica PURGED is a replay
            # of purged state — the stability watermark proves every
            # peer delivered everything below the floor (drift slack
            # included), so nothing below it is legitimately still in
            # flight for a purged slot. Rows at or above the floor,
            # sub-floor rows for never-purged slots (first-time
            # deliveries: migration streams, initial syncs), and rows
            # the join would dominate anyway all pass through.
            fenced = np.asarray(jax.device_get(
                self._gc_fence_dev[np.asarray(slots)]))
            stale = (lt <= floor) & fenced
            if stale.any():
                from ..obs.registry import default_registry
                default_registry().counter(
                    "crdt_tpu_gc_fenced_rows_total",
                    "inbound rows dropped by the post-GC resurrection "
                    "fence").inc(int(stale.sum()),
                                 node=str(self._node_id))
                keep = ~stale
                slots, lt, node, val, tomb = (
                    slots[keep], lt[keep], node[keep], val[keep],
                    tomb[keep])
                if not len(slots):
                    # Same two ticks as the withheld-empty path above.
                    self._wall_clock()
                    self._canonical_time = Hlc.send(
                        self._canonical_time,
                        millis=self._wall_clock())
                    return None
        k = len(slots)
        my_ord = self._table.ordinal(self._node_id)
        wall = self._wall_clock()

        # Recv guards + clock absorption against the RUNNING canonical
        # (exclusive cummax — hlc.dart:85's fast path shields records
        # the clock already dominates), in payload visit order, shared
        # with the other host backends (utils/host_guards.py).
        from ..utils.host_guards import recv_fold_columns
        fold = recv_fold_columns(lt, node == my_ord,
                                 self._canonical_time.logical_time, wall)
        if fold.bad_index is not None:
            # Canonical partially advanced to just before the offender
            # (sequential-merge parity, crdt.dart:77-94 throw path);
            # store untouched.
            self._canonical_time = Hlc.from_logical_time(
                fold.canonical_at_fail, self._node_id)
            if fold.bad_is_dup:
                raise DuplicateNodeException(str(self._node_id))
            raise ClockDriftException(int(lt[fold.bad_index]) >> 16, wall)
        new_canonical = fold.new_canonical

        with merge_annotation("crdt_tpu.dense_merge",
                              hlc=lambda: self._canonical_time):
            new_store, win, slot_aligned, repack_mask = \
                self._dispatch_columns(slots, lt, node, val, tomb,
                                       new_canonical, my_ord,
                                       repack_since_lt=repack_since_lt)
        self._store = self._postprocess_store(new_store)
        # The join produced fresh buffers (the old lanes were consumed
        # — donated when eligible); the next columnar merge may donate
        # them again, keeping repeated gossip rounds at the in-place
        # dispatch floor.
        self._store_escaped = False
        if _sanitizer.enabled():
            # Callers collapse duplicate slots before reaching here
            # (same contract the merge itself needs), so the
            # payload-order domination check is well-defined.
            _sanitizer.check_dense_sparse_join(self._store, slots, lt,
                                               node)
            if self._gc_purged is not None:
                _sanitizer.check_dense_no_resurrection(
                    self._store, *self._gc_purged)

        if self._hub.active:
            win_full = np.asarray(jax.device_get(win))
            # The wide join reports win per SLOT; re-align to payload
            # order so events keep the reference's visit order.
            win_h = win_full[slots] if slot_aligned else win_full[:k]
            self.stats.records_adopted += int(win_h.sum())
            widx = np.nonzero(win_h)[0]

            def value_at(i):
                return None if tomb[i] else int(val[i])

            # Both callers (`_merge_columns` and the C wire-scan path)
            # collapse duplicate slots last-wins before reaching here,
            # so a queried slot matches AT MOST one payload entry —
            # the get callback can never answer with a losing
            # occurrence's value (ChangeHub.add_batch's contract).
            # crdtlint: disable=add-batch-unique-keys -- duplicate slots are collapsed last-wins by both callers before reaching here (see above)
            self._hub.add_batch(
                lambda: ([int(slots[i]) for i in widx],
                         [value_at(i) for i in widx]),
                lambda q: ((True,
                            value_at(int(np.nonzero(slots == q)[0][-1])))
                           if isinstance(q, (int, np.integer))
                           and bool(np.any(slots[widx] == q))
                           else (False, None)))
        else:
            # No subscriber: keep the win mask on device — the warm
            # sparse path then has ZERO device->host fetches (each one
            # is a full round trip on remote-proxied backends); the
            # adopted counter drains lazily when stats are read.
            self.stats.add_adopted_lazy(jnp.sum(win))
        self._canonical_time = Hlc.send(
            Hlc.from_logical_time(new_canonical, self._node_id),
            millis=self._wall_clock())
        return repack_mask

    # Above this fraction of the slot space a columnar delta executes
    # as the elementwise N-wide join instead of the k-index scatter:
    # TPU scatters serialize per index (~0.3 s for 1M indices on v5e),
    # while the slot-aligned compare/select sweep is one fused
    # elementwise pass; the host-side fancy-write that builds the
    # N-wide lanes costs ~30 ms at 1M. Below the threshold the O(k)
    # scatter wins (a 10-record sync must not touch N-wide lanes).
    WIDE_JOIN_FRACTION = 4

    def _dispatch_columns(self, slots, lt, node, val, tomb,
                          new_canonical: int, my_ord: int,
                          repack_since_lt: Optional[int] = None):
        """Run a validated columnar delta through the store join.
        Returns ``(new_store, win, slot_aligned, repack_mask)`` —
        ``win`` is per SLOT (N-wide) when ``slot_aligned``, else per
        payload entry. ``repack_mask`` is the fused next-pack delta
        mask when ``repack_since_lt`` was requested AND the sparse
        fused kernel ran; None on every other route."""
        if self._sem is not None:
            return self._dispatch_columns_typed(
                slots, lt, node, val, tomb, new_canonical,
                my_ord) + (None,)
        k = len(slots)
        n = self.n_slots
        if k * self.WIDE_JOIN_FRACTION >= n:
            lt_n = np.zeros((n,), np.int64)
            node_n = np.zeros((n,), np.int16
                              if len(self._table) <= 0x7FFF else np.int32)
            tomb_n = np.zeros((n,), bool)
            valid_n = np.zeros((n,), bool)
            lt_n[slots] = lt
            node_n[slots] = node
            tomb_n[slots] = tomb
            valid_n[slots] = True
            # Narrow the value lane to int32 when every value fits —
            # the transfer is the wide join's main cost and the jit
            # widens on device (value_width=32 replicas always fit).
            if self._value_width == 32 or (
                    k and -(2 ** 31) <= int(val.min())
                    and int(val.max()) < 2 ** 31):
                val_n = np.zeros((n,), np.int32)
            else:
                val_n = np.zeros((n,), np.int64)
            val_n[slots] = val
            from ..ops.dense import wire_join_step
            new_store, win = wire_join_step(
                self._store, jnp.asarray(lt_n), jnp.asarray(node_n),
                jnp.asarray(val_n), jnp.asarray(tomb_n),
                jnp.asarray(valid_n), jnp.int64(new_canonical),
                jnp.int32(my_ord), donate=self._donate_writes(),
                sharding=self._write_sharding())
            return new_store, win, True, None
        # Pad k to a power of two (invalid rows scatter to the n_slots
        # sentinel, mode="drop") so the jitted step compiles O(log k)
        # distinct shapes, not one per delta size.
        padded = 1 << max(k - 1, 1).bit_length()
        lt_p = np.zeros((padded,), np.int64)
        node_p = np.zeros((padded,), np.int32)
        val_p = np.zeros((padded,), np.int64)
        tomb_p = np.zeros((padded,), bool)
        valid = np.zeros((padded,), bool)
        slot_arr = np.full((padded,), self.n_slots,
                           np.int32 if self.n_slots < 2 ** 31 - 1
                           else np.int64)
        slot_arr[:k] = slots
        valid[:k] = True
        lt_p[:k] = lt
        node_p[:k] = node
        val_p[:k] = val
        tomb_p[:k] = tomb
        if repack_since_lt is not None:
            # Fused relay: the join AND the next pack's delta mask come
            # out of ONE jitted program — no second dispatch between a
            # gossip merge and the reply pack (docs/FASTPATH.md).
            new_store, win, mask = merge_repack_step(
                self._store, jnp.asarray(slot_arr), jnp.asarray(lt_p),
                jnp.asarray(node_p), jnp.asarray(val_p),
                jnp.asarray(tomb_p), jnp.asarray(valid),
                jnp.int64(new_canonical), jnp.int32(my_ord),
                jnp.int64(repack_since_lt),
                donate=self._donate_writes(),
                sharding=self._write_sharding())
            return new_store, win, False, mask
        new_store, win = sparse_fanin_step(
            self._store, jnp.asarray(slot_arr), jnp.asarray(lt_p),
            jnp.asarray(node_p), jnp.asarray(val_p),
            jnp.asarray(tomb_p), jnp.asarray(valid),
            jnp.int64(new_canonical), jnp.int32(my_ord),
            donate=self._donate_writes(), sharding=self._write_sharding())
        return new_store, win, False, None

    def _dispatch_columns_typed(self, slots, lt, node, val, tomb,
                                new_canonical: int, my_ord: int):
        """The typed counterpart of `_dispatch_columns`: same
        wide-vs-sparse cutover, but routed through the semantics
        kernels with the per-slot (wide) or per-row (sparse) tag lane.
        The value lane stays int64 — typed encodings use all 64 bits,
        so the wide path's int32 narrowing never applies."""
        from ..semantics.kernels import (typed_sparse_join_step,
                                         typed_wire_join_step)
        k = len(slots)
        n = self.n_slots
        if k * self.WIDE_JOIN_FRACTION >= n:
            lt_n = np.zeros((n,), np.int64)
            node_n = np.zeros((n,), np.int32)
            val_n = np.zeros((n,), np.int64)
            tomb_n = np.zeros((n,), bool)
            valid_n = np.zeros((n,), bool)
            lt_n[slots] = lt
            node_n[slots] = node
            val_n[slots] = val
            tomb_n[slots] = tomb
            valid_n[slots] = True
            new_store, win = typed_wire_join_step(
                self._store, self._sem_device(), jnp.asarray(lt_n),
                jnp.asarray(node_n), jnp.asarray(val_n),
                jnp.asarray(tomb_n), jnp.asarray(valid_n),
                jnp.int64(new_canonical), jnp.int32(my_ord),
                donate=self._donate_writes(),
                sharding=self._write_sharding())
            return new_store, win, True
        padded = 1 << max(k - 1, 1).bit_length()
        sem_rows = np.zeros((padded,), np.int8)
        lt_p = np.zeros((padded,), np.int64)
        node_p = np.zeros((padded,), np.int32)
        val_p = np.zeros((padded,), np.int64)
        tomb_p = np.zeros((padded,), bool)
        valid = np.zeros((padded,), bool)
        slot_arr = np.full((padded,), self.n_slots, np.int32)
        slot_arr[:k] = slots
        sem_rows[:k] = self._sem[slots]
        valid[:k] = True
        lt_p[:k] = lt
        node_p[:k] = node
        val_p[:k] = val
        tomb_p[:k] = tomb
        new_store, win = typed_sparse_join_step(
            self._store, jnp.asarray(sem_rows), jnp.asarray(slot_arr),
            jnp.asarray(lt_p), jnp.asarray(node_p), jnp.asarray(val_p),
            jnp.asarray(tomb_p), jnp.asarray(valid),
            jnp.int64(new_canonical), jnp.int32(my_ord),
            donate=self._donate_writes(),
            sharding=self._write_sharding())
        return new_store, win, False

    # --- checkpoint/resume (SURVEY.md §5) ---

    def save(self, path: str) -> None:
        """Columnar snapshot INCLUDING the node-id table the ordinal
        lanes index into (`crdt_tpu.checkpoint.save_dense`) AND the
        Merkle digest tree under its cache key — a restarted replica
        answers its first anti-entropy walk from the persisted tree
        with zero digest dispatches (docs/ANTIENTROPY.md). The tree
        comes from the digest cache when the store is quiet, so a
        save after a walk adds no device work."""
        self.drain_ingest()
        from ..checkpoint import save_dense
        tree = self.digest_tree()
        save_dense(self._store, path,
                   node_ids=self._table.ids(),
                   digest=(tree, self._canonical_time.logical_time,
                           self._sem_version))

    @classmethod
    def load(cls, node_id: Any, path: str,
             wall_clock: Optional[Callable[[], int]] = None,
             **kwargs) -> "DenseCrdt":
        """Resume from a snapshot; the canonical clock rebuilds from the
        lanes (refreshCanonicalTime semantics, crdt.dart:31-33) and
        writer attribution survives via the persisted node table. A
        persisted digest tree re-seeds the digest cache when its key
        still matches the rebuilt state — guarded on clock, semantics
        version, and geometry, so a stale or foreign tree silently
        falls back to rebuild-on-first-walk."""
        from ..checkpoint import load_dense_digest, \
            load_dense_with_node_ids
        store, ids = load_dense_with_node_ids(path)
        if ids is None:
            # A lane-only snapshot's ordinals are uninterpretable here;
            # constructing a replica anyway would silently re-attribute
            # (or crash on) every foreign record.
            raise ValueError(
                f"{path} has no node-id table (store-level snapshot); "
                "use DenseCrdt.save for resumable snapshots, or pass "
                "store=load_dense(path) with the original node_ids")
        crdt = cls(node_id, store.n_slots, wall_clock=wall_clock,
                   store=store, node_ids=ids, **kwargs)
        restored = load_dense_digest(path)
        if restored is not None:
            tree, logical_time, sem_version = restored
            # Seed AFTER construction: the _store setter in __init__
            # cleared the cache, and the guards below are what make
            # the seed sound (same clock head, same semantics column
            # version, same tree geometry as this replica would build).
            if (logical_time == crdt._canonical_time.logical_time
                    and sem_version == crdt._sem_version
                    and tree.n_slots == crdt.n_slots
                    and tree.leaf_width == crdt.DIGEST_LEAF_WIDTH):
                # Key under the LIVE generation: the snapshot's counter
                # is meaningless here, and the guards above prove the
                # tree matches the state this generation names.
                crdt._digest_cache = (crdt._digest_key(), tree)
        return crdt

    # --- replication (C9/C10) ---

    def export_delta(self, since: Optional[Hlc] = None
                     ) -> Tuple[DenseChangeset, List[Any]]:
        """Outbound changeset: full state, or records with
        ``modified >= since`` (inclusive, map_crdt.dart:44-45), plus the
        node-id list its ordinals index into."""
        self.drain_ingest()
        since_lt = None if since is None else jnp.int64(since.logical_time)
        # store_to_changeset reshapes lanes; whether jax aliases the
        # underlying buffers is backend-dependent, so treat the export
        # as an escape — later writes must not donate those buffers.
        self._store_escaped = True
        cs = store_to_changeset(self._store, since_lt)
        return cs, self._table.ids()

    def _fit_slots(self, cs: DenseChangeset) -> DenseChangeset:
        """Normalize a peer changeset's slot width to this replica's
        capacity: a NARROWER peer (pre-`grow` rollout) pads with
        invalid lanes; a WIDER one would silently drop records past
        capacity, so it raises with the remedy instead of dying in an
        XLA shape error."""
        width = cs.lt.shape[1]
        if width == self.n_slots:
            return cs
        if width > self.n_slots:
            raise ValueError(
                f"peer changeset covers {width} slots but this replica "
                f"holds {self.n_slots}; call grow({width}) first")
        pad = self.n_slots - width
        return DenseChangeset(
            lt=jnp.pad(cs.lt, ((0, 0), (0, pad))),
            node=jnp.pad(cs.node, ((0, 0), (0, pad))),
            val=jnp.pad(cs.val, ((0, 0), (0, pad))),
            tomb=jnp.pad(cs.tomb, ((0, 0), (0, pad))),
            valid=jnp.pad(cs.valid, ((0, 0), (0, pad))),
        )

    def _intern_ids(self, node_ids: Sequence[Any]) -> None:
        """Intern ids into the table, re-encoding stored lanes when new
        ids shift existing ordinals."""
        remap_store = self._table.intern(list(node_ids))
        if remap_store is not None:
            rd = jnp.asarray(remap_store)
            self._store = self._store._replace(
                node=rd[self._store.node],
                mod_node=rd[self._store.mod_node])

    def _encode_peer(self, cs: DenseChangeset, node_ids: Sequence[Any]
                     ) -> DenseChangeset:
        """Rewrite a changeset's ordinals into this replica's table.
        Every id in ``node_ids`` must already be interned — encoding
        against a table that can still shift corrupts earlier-encoded
        changesets (the round-1 stale-ordinal bug)."""
        remap = [self._table.ordinal(n) for n in node_ids]
        if remap == list(range(len(self._table))):
            # Peer table == local table (the steady gossip state):
            # the gather would rewrite an identical [R, N] node lane.
            return cs
        peer_to_local = jnp.asarray(remap, jnp.int32)
        return cs._replace(node=peer_to_local[cs.node])

    # Above this many replica rows the fold is executed as a lax.scan
    # over fixed-size chunks instead of a Python-unrolled [R, N] batch:
    # compile time stays flat in the peer count and one compiled step
    # serves every stream length. Results are bit-identical (the stream
    # is stamped with the union-final canonical).
    STREAM_THRESHOLD_ROWS = 16
    STREAM_CHUNK_ROWS = 8

    def _use_pallas(self) -> bool:
        """Route merges through the Mosaic kernel? ``executor=`` forces
        it on ("pallas" / "pallas-interpret") or off ("xla"); "auto"
        takes the kernel whenever the store is tile-aligned, the node
        table fits the kernel's int16 wire lane, and the backend is an
        accelerator."""
        if self._sem is not None:
            # Typed stores join through the semantics kernels (XLA
            # elementwise); the Mosaic kernel is LWW-only.
            # `set_semantics` refuses forced-pallas executors, so this
            # auto-fallback never contradicts an explicit request.
            return False
        from ..ops.pallas_merge import MAX_NODE_ORDINAL, TILE
        if len(self._table) > MAX_NODE_ORDINAL:
            # The kernel's changeset node lane is int16 (ordinals are
            # distinct-replica counts); a table past 32k ordinals
            # routes to the XLA fold rather than wrapping silently.
            if self._executor in ("pallas", "pallas-interpret"):
                raise ValueError(
                    f"executor={self._executor!r} supports at most "
                    f"{MAX_NODE_ORDINAL} node ordinals; table holds "
                    f"{len(self._table)}")
            return False
        if self._executor == "xla":
            return False
        if self._executor in ("pallas", "pallas-interpret"):
            return True
        # Mosaic lowers on TPU only — a GPU backend must keep the XLA
        # fold, not crash in pltpu BlockSpecs.
        return (self.n_slots % TILE == 0
                and jax.devices()[0].platform == "tpu")

    def _dispatch_fanin(self, cs: DenseChangeset, wall: int):
        """Run the fan-in join; subclasses route to other executors.
        Returns ``(new_store, res)`` with a FaninResult-compatible res."""
        canonical = self._canonical_lt()
        local = jnp.int32(self._table.ordinal(self._node_id))
        if self._sem is not None:
            return self._typed_fanin(cs, canonical, local, wall)
        if self._use_pallas():
            return self._dispatch_pallas(cs, canonical, local, wall)
        r = cs.lt.shape[0]
        if r <= self.STREAM_THRESHOLD_ROWS:
            return fanin_step(self._store, cs, canonical, local,
                              jnp.int64(wall))
        rc = self.STREAM_CHUNK_ROWS
        cs = pad_replica_rows(cs, rc)
        chunks = DenseChangeset(*(
            lane.reshape(-1, rc, lane.shape[1]) for lane in cs))
        stamp = jnp.maximum(canonical,
                            jnp.max(jnp.where(cs.valid, cs.lt, _NEG)))
        return fanin_stream(self._store, chunks, canonical, local,
                            jnp.int64(wall), stamp)

    def _typed_fanin(self, cs: DenseChangeset, canonical, local,
                     wall: int):
        """Changeset fan-in on a typed store: the semantics kernels'
        Python-unrolled elementwise fold. Shared by the base AND
        sharded models — typed joins are purely elementwise, so the
        sharded store runs the same jit with its key-axis sharding
        pinned, no collective dispatch (replica rows fold locally
        against key-sharded lanes). Guard flags here are exact (same
        `recv_guards` as the XLA fold), so `_exact_guards` passes the
        result through unchanged."""
        from ..semantics.kernels import typed_fanin_step
        return typed_fanin_step(self._store, self._sem_device(), cs,
                                canonical, local, jnp.int64(wall),
                                sharding=self._write_sharding())

    def _dispatch_pallas(self, cs: DenseChangeset, canonical, local,
                         wall: int):
        """The Mosaic executor — ONE fused dispatch
        (`model_fanin_batch`): lane split/narrowing, value-width
        masking, seen count, the batch kernel, and the store re-join
        all inside a single jit, because on remote-proxied backends
        each separate dispatch is a host round trip (optimistic guard
        flags — `_exact_guards` recomputes on a trip because the
        result carries no first-offender fields)."""
        from ..ops.pallas_merge import model_fanin_batch
        r = cs.lt.shape[0]
        chunk = self._kernel_chunk_rows(r)
        if chunk < r:
            cs = pad_replica_rows(cs, chunk)
        new_store, pres, seen, voverflow = model_fanin_batch(
            self._store, cs, canonical, local, jnp.int64(wall),
            chunk_rows=chunk,
            interpret=self._executor == "pallas-interpret",
            value_width=self._value_width)
        self.stats.add_seen_lazy(seen)
        if self._value_width == 32:
            self._pending_val_overflow = voverflow
        return new_store, self._pallas_result(pres)

    def _kernel_chunk_rows(self, r: int) -> int:
        """Chunk sizing for the batch kernel: small changesets (the
        common gossip delta) take ``chunk_rows=r`` and skip the row
        padding entirely — the eager pad concatenate writes chunk_rows
        full-width lanes (~24 ms for 8×1M on the proxied chip), more
        than the whole merge. Cost: each distinct r ≤ 8 compiles its
        own kernel once (bounded at 8 shapes; steady gossip reuses
        one), which the padding saving repays within a handful of
        merges."""
        return r if r <= self.STREAM_CHUNK_ROWS else self.STREAM_CHUNK_ROWS

    @staticmethod
    def _pallas_result(pres) -> FaninResult:
        """Adapt a `PallasFaninResult` (optimistic superset flags, no
        first-offender fields) to the model-layer FaninResult shape —
        `_exact_guards` recomputes on host when a flag trips."""
        return FaninResult(
            new_canonical=pres.new_canonical,
            win_count=jnp.sum(pres.win).astype(jnp.int32),
            win=pres.win,
            any_bad=pres.any_dup | pres.any_drift,
            first_bad=None, first_is_dup=None, canonical_at_fail=None)

    def _exact_guards(self, cs: DenseChangeset, res, wall: int):
        """Exact r-major sequential guard diagnostics (the visit order
        of crdt.dart:80-94). The XLA fan-in's flags are already exact
        and carry first-offender fields — returned as-is. Executors
        with coarse/superset flags (the sharded collectives, the
        optimistic Pallas guards) produce results WITHOUT
        ``first_bad``; recompute exactly on the unsharded changeset —
        failure path only — so raised exceptions carry the sequential
        path's first-offender payload, and false positives are cleared
        (None → merge proceeds)."""
        if getattr(res, "first_bad", None) is not None:
            return res
        any_bad, first_bad, first_is_dup, canonical_at_fail = recv_guards(
            cs.lt, cs.node, cs.valid,
            jnp.int64(self._canonical_time.logical_time),
            jnp.int32(self._table.ordinal(self._node_id)),
            jnp.int64(wall))
        if not bool(any_bad):
            return None
        return FaninResult(
            new_canonical=res.new_canonical, win_count=res.win_count,
            win=res.win, any_bad=any_bad, first_bad=first_bad,
            first_is_dup=first_is_dup, canonical_at_fail=canonical_at_fail)

    def _postprocess_store(self, store: DenseStore) -> DenseStore:
        """Hook for subclasses to re-annotate a freshly written store
        (the sharded model re-applies its NamedSharding here)."""
        return store

    def _use_pallas_scatter(self) -> bool:
        """Route the ingest commit through the touched-tile Mosaic
        kernel? Stamp-blind overwrites don't care about the store's
        semantics tags or table width, so the gates are only tile
        alignment and a backend Mosaic can lower on (interpret mode
        stands in off-TPU when forced)."""
        from ..ops.pallas_merge import TILE
        if self.n_slots % TILE:
            return False
        if self._executor == "xla":
            return False
        if self._executor in ("pallas", "pallas-interpret"):
            return True
        return jax.devices()[0].platform == "tpu"

    def _commit_scatter(self, slots: np.ndarray, lt: np.ndarray,
                        vals: np.ndarray, tombs: np.ndarray
                        ) -> DenseStore:
        """ONE device dispatch committing a deduped ingest batch
        (`WriteCombiner.flush`'s scatter tail). Picks the touched-tile
        Mosaic kernel when it engages, else the lax scatter with
        power-of-two padded lanes; the sharded model overrides this
        with one `shard_map` program (docs/FASTPATH.md)."""
        me = self._table.ordinal(self.node_id)
        if self._use_pallas_scatter():
            from ..ops.pallas_scatter import ingest_scatter_tiles
            # crdtlint: disable=scatter-combiner-bypass -- only reached from the combiner's own flush, which IS the barrier
            return ingest_scatter_tiles(
                self._store, slots, lt, vals, tombs, me,
                donate=self._donate_writes(),
                interpret=self._executor == "pallas-interpret")
        # Fresh padded commit lanes every flush (power-of-two + slot ==
        # n_slots sentinel rows, mode="drop"): the dispatch owns them
        # outright, so the combiner's stage-side buffers are
        # immediately reusable — the double-buffer that lets the host
        # stage flush N+1 while N executes.
        d = len(slots)
        padded = 1 << max(d - 1, 1).bit_length()
        slot_l = np.full(padded, self.n_slots, np.int32)
        lt_l = np.zeros(padded, np.int64)
        val_l = np.zeros(padded, np.int64)
        tomb_l = np.zeros(padded, bool)
        slot_l[:d] = slots
        lt_l[:d] = lt
        val_l[:d] = vals
        tomb_l[:d] = tombs
        from ..ops.dense import ingest_scatter
        sharding = self._write_sharding()
        # crdtlint: disable=scatter-combiner-bypass -- only reached from the combiner's own flush, which IS the barrier
        new_store = ingest_scatter(
            self._store, jnp.asarray(slot_l), jnp.asarray(lt_l),
            jnp.asarray(val_l), jnp.asarray(tomb_l), jnp.int32(me),
            donate=self._donate_writes(), sharding=sharding)
        # The in-jit constraint already pinned the layout; skip the
        # subclass re-shard round-trip in that case.
        return new_store if sharding is not None \
            else self._postprocess_store(new_store)

    def _raise_guard(self, cs: DenseChangeset, res, wall: int) -> None:
        # Store untouched; canonical rolled to the pre-failure value
        # (sequential-merge parity, crdt.dart:77-94 throw path).
        self._canonical_time = Hlc.from_logical_time(
            int(res.canonical_at_fail), self._node_id)
        if bool(res.first_is_dup):
            raise DuplicateNodeException(str(self._node_id))
        bad_lt = int(cs.lt.reshape(-1)[int(res.first_bad)])
        raise ClockDriftException(bad_lt >> 16, wall)

    def merge(self, cs, node_ids: Optional[Sequence[Any]] = None) -> None:
        """Fan-in a peer changeset. ``cs.node`` ordinals index
        ``node_ids``; they are remapped into this replica's table.

        Also accepts a record dict (slot → Record) for duck-type
        compatibility with the `Crdt.merge` surface — `crdt_tpu.sync`
        rounds then work across dense and record-dict backends alike."""
        if isinstance(cs, dict):
            return self.merge_records(cs)
        if node_ids is None:
            raise ValueError(
                "merge(changeset) requires node_ids — the changeset's "
                "ordinals are meaningless without the table they index")
        self.merge_many([(cs, node_ids)])

    def merge_many(self, changesets: Sequence[
            Tuple[DenseChangeset, Sequence[Any]]]) -> None:
        """N-replica fan-in: concatenate peer changesets along the
        replica axis (earlier entries win identical-HLC ties, the
        sequential-merge order) and run ONE fused lattice join."""
        self.drain_ingest()
        self.stats.merges += 1
        if not changesets:
            # Merging nothing still consumes the absorption-phase wall
            # read AND the final send bump (crdt.dart:77-94 reads the
            # clock before the record loop regardless, then sends) —
            # the same two ticks every record-dict backend spends, so
            # cross-backend differentials under an injected clock
            # can't drift on empty anti-entropy rounds.
            self._wall_clock()
            if self._pipe is not None:
                # empty merges still occupy a window slot so the
                # flush's first-flag index stays aligned with the
                # caller's merge order
                self._pipe.merges += 1
                self._pipe_send_bump(self._wall_clock())
                return
            self._canonical_time = Hlc.send(self._canonical_time,
                                            millis=self._wall_clock())
            return
        # Intern the UNION of every peer's ids first — one table
        # mutation, one store re-encode — then encode each changeset
        # against the now-final table. Interleaving interning with
        # encoding left earlier-encoded changesets holding stale
        # ordinals whenever a later peer's ids re-sorted the table.
        union: set = set()
        for _, ids in changesets:
            union.update(ids)
        self._intern_ids(union)
        parts = [self._encode_peer(self._fit_slots(cs), ids)
                 for cs, ids in changesets]
        # Single-peer merges (the common gossip round) skip the concat
        # entirely — jnp.concatenate of one part still copies [R, N]
        # lanes.
        cs = parts[0] if len(parts) == 1 else DenseChangeset(
            *(jnp.concatenate([getattr(p, f) for p in parts])
              for f in DenseChangeset._fields))
        if self._gc_floor_lt and self._gc_fence_dev is not None:
            # Device-side resurrection fence for wide changesets —
            # same predicate as the columnar path in _merge_validated
            # (sub-floor row onto a PURGED slot = replay of purged
            # state); stays a mask fold, no host sync.
            cs = cs._replace(valid=cs.valid & ~(
                (cs.lt <= jnp.int64(self._gc_floor_lt))
                & self._gc_fence_dev[None, :]))
        pipe = self._pipe
        if pipe is not None and not pipe.exact and self._use_pallas():
            # Coarse pipelined Mosaic merges run as ONE dispatch
            # (merge + flag accumulation + send bump fused): the
            # separate bookkeeping ops each cost a host round trip on
            # remote-proxied backends and were the dominant share of
            # the north-star e2e pass. Exact-guard windows keep the
            # stepwise path (their guard pass needs the wide lanes).
            from ..ops.pallas_merge import pipelined_model_step
            r = cs.lt.shape[0]
            chunk = self._kernel_chunk_rows(r)
            if chunk < r:
                cs = pad_replica_rows(cs, chunk)
            # Both wall reads up front (absorption + send bump): same
            # count and sequence as the unfused path, so injected
            # clocks tick identically.
            wall_merge = self._wall_clock()
            wall_send = self._wall_clock()
            with merge_annotation("crdt_tpu.dense_merge",
                                  hlc=lambda: self._canonical_time):
                (new_store, new_canon, any_bad, overflow, drift,
                 val_ovf, first_idx, win_count, win, seen) = \
                    pipelined_model_step(
                        self._store, cs, pipe.canonical, pipe.any_bad,
                        pipe.overflow, pipe.drift, pipe.val_overflow,
                        pipe.first_flag_idx,
                        jnp.int32(self._table.ordinal(self._node_id)),
                        jnp.int64(wall_merge), jnp.int64(wall_send),
                        jnp.int32(pipe.merges),
                        chunk_rows=chunk,
                        interpret=self._executor == "pallas-interpret",
                        value_width=self._value_width)
            pipe.canonical = new_canon
            pipe.any_bad = any_bad
            pipe.overflow = overflow
            pipe.drift = drift
            pipe.val_overflow = val_ovf
            pipe.first_flag_idx = first_idx
            pipe.merges += 1
            self._store = self._postprocess_store(new_store)
            self.stats.add_seen_lazy(seen)
            self.stats.add_adopted_lazy(win_count)
            self._emit_merge_wins(new_store, win)
            return
        if not self._use_pallas():
            # The Mosaic route folds BOTH of these into its single
            # fused dispatch (`model_fanin_batch`); the other
            # executors run them as standalone device ops here.
            if self._value_width == 32:
                # Uniform value-ref enforcement: records whose values
                # don't round-trip through int32 are masked INVALID
                # before dispatch — they never merge, so no truncated
                # or unnarrowed payload can land under the peer's
                # winning HLC — and the flag reports at the next
                # batched fetch / pipeline flush.
                fits = (cs.val.astype(jnp.int32).astype(jnp.int64)
                        == cs.val)
                self._pending_val_overflow = jnp.any(cs.valid & ~fits)
                cs = cs._replace(valid=cs.valid & fits)
            # Lazy device scalar: no device->host sync on the hot path.
            self.stats.add_seen_lazy(jnp.sum(cs.valid))

        wall = self._wall_clock()
        with merge_annotation("crdt_tpu.dense_merge",
                              hlc=lambda: self._canonical_time):
            new_store, res = self._dispatch_fanin(cs, wall)

        voverflow, self._pending_val_overflow = \
            self._pending_val_overflow, None
        self._finish_merge(new_store, res, voverflow, wall, lambda: cs)

    def _finish_merge(self, new_store, res, voverflow, wall: int,
                      cs_for_exact: Callable[[], DenseChangeset],
                      guard_lanes: Optional[Callable] = None) -> None:
        """Shared post-dispatch tail for changeset merges
        (`merge_many` / `merge_split`): the pipelined accumulation OR
        the one batched fetch + value-overflow reject + exact-guard
        recompute + store swap + stats + watch + final send bump.
        ``cs_for_exact`` lazily produces the WIDE changeset for the
        failure-path guard recompute — outside exact-mode windows,
        pre-split callers only pay the reconstruction when a flag
        actually trips. In an ``exact_guards`` window the guard lanes
        are needed EVERY merge; ``guard_lanes`` (a thunk returning
        ``(lt, node, valid)``) lets such callers supply just the three
        lanes the guards read instead of the full wide changeset."""
        if self._pipe is not None:
            # Pipelined tail: nothing leaves the device. Guard flags
            # OR-accumulate; the canonical threads through the device
            # send bump; the adopted counter drains lazily.
            pipe = self._pipe
            if pipe.exact:
                # One exact pass (cost: the running-cummax sweep the
                # fast kernels skip, plus — for pre-split callers —
                # the guard-lane reconstruction), seeded with the
                # threaded pre-merge canonical. The executor's
                # superset flags are superseded entirely.
                if guard_lanes is not None:
                    g_lt, g_node, g_valid = guard_lanes()
                else:
                    cs = cs_for_exact()
                    g_lt, g_node, g_valid = cs.lt, cs.node, cs.valid
                any_b, bad_lt, first_is_dup, caf = _pipe_exact_guards(
                    g_lt, g_node, g_valid, pipe.canonical,
                    jnp.int32(self._table.ordinal(self._node_id)),
                    jnp.int64(wall))
                newly = (~pipe.ex_have) & any_b
                pipe.ex_dup = jnp.where(newly, first_is_dup,
                                        pipe.ex_dup)
                pipe.ex_lt = jnp.where(newly, bad_lt, pipe.ex_lt)
                pipe.ex_caf = jnp.where(newly, caf, pipe.ex_caf)
                pipe.ex_wall = jnp.where(newly, jnp.int64(wall),
                                         pipe.ex_wall)
                pipe.ex_have = pipe.ex_have | any_b
                recv_flag = any_b
            else:
                recv_flag = res.any_bad
            new_flags = recv_flag
            if voverflow is not None:
                pipe.val_overflow = pipe.val_overflow | voverflow
                new_flags = new_flags | voverflow
            pipe.note(new_flags)
            pipe.any_bad = pipe.any_bad | recv_flag
            pipe.merges += 1
            self._store = new_store
            self.stats.add_adopted_lazy(res.win_count)
            self._emit_merge_wins(new_store, res.win)
            pipe.canonical = res.new_canonical
            self._pipe_send_bump(self._wall_clock())
            return

        # The small result scalars come back in ONE batched fetch: on
        # remote-proxied backends each separate readback is a full
        # round trip. The [N] win mask stays on device unless a watch
        # subscriber needs it.
        if voverflow is None:
            any_bad, win_count, new_canonical = jax.device_get(
                (res.any_bad, res.win_count, res.new_canonical))
            val_ovf = False
        else:
            any_bad, win_count, new_canonical, val_ovf = jax.device_get(
                (res.any_bad, res.win_count, res.new_canonical,
                 voverflow))
        if bool(val_ovf):
            # Raised BEFORE the store swap: the merge is rejected
            # whole (replica untouched; the offending records were
            # additionally masked out of the join), matching the
            # host-side write validation.
            raise ValueError(
                "value_width=32 replica merged a changeset holding "
                "values outside int32 range; use a value_width=64 "
                "replica (or payload-table indices) for such data")

        if bool(any_bad):
            cs = cs_for_exact()
            exact = self._exact_guards(cs, res, wall)
            if exact is not None:
                self._raise_guard(cs, exact, wall)
            # else: a coarser executor's guard flagged a record the
            # exact sequential order shields — proceed (store lanes
            # are bit-identical either way).

        self._store = new_store
        if _sanitizer.enabled():
            # Wide post-state check against the merged changeset. The
            # pipelined branch above is exempt BY CONTRACT: it promises
            # zero host syncs per merge, which a host-side assertion
            # would break — sanitize soaks run unpipelined.
            _sanitizer.check_dense_join(self._store, cs_for_exact())
            if self._gc_purged is not None:
                _sanitizer.check_dense_no_resurrection(
                    self._store, *self._gc_purged)
        self.stats.records_adopted += int(win_count)
        self._emit_merge_wins(new_store, res.win)
        self._canonical_time = Hlc.send(
            Hlc.from_logical_time(int(new_canonical), self._node_id),
            millis=self._wall_clock())

    # --- pre-split interchange (the kernel wire form, round 5) ---

    def export_split_delta(self, since: Optional[Hlc] = None,
                           tiled: bool = True):
        """Outbound changeset in the KERNEL WIRE FORM — split 32-bit
        lanes (`ops.pallas_merge.SplitChangeset`, or the narrow
        value-ref lanes on a ``value_width=32`` replica), pre-tiled to
        the kernel's resident layout when the capacity allows. What
        `merge_split` consumes with ZERO per-merge conversion: gossip
        peers exchanging this form skip both the int64 split and the
        tile relayout copy on every merge (each measured comparable to
        the join itself — docs/PERF.md round 5). Returns
        ``(split_changeset, node_ids)``."""
        from ..ops.pallas_merge import (TILE, split_changeset,
                                        split_changeset_narrow,
                                        tile_changeset)
        cs, ids = self.export_delta(since)
        if self._value_width == 32:
            # Values were range-checked on every ingest path; the
            # overflow flag is structurally False here.
            scs, _ = split_changeset_narrow(cs)
        else:
            scs = split_changeset(cs)
        if tiled and self.n_slots % TILE == 0:
            scs = tile_changeset(scs)
        return scs, ids

    def merge_split(self, scs, node_ids: Sequence[Any]) -> None:
        """Fan-in a PRE-SPLIT (optionally pre-tiled) changeset — the
        zero-conversion counterpart of ``merge(cs, node_ids)`` for
        peers exchanging `export_split_delta`'s wire form. Semantics
        (guards, value-width enforcement, pipelined windows, watch,
        stats, clock) are identical to the wide path; on executors
        without the Mosaic kernel the lanes are joined back to wide
        form and merged through ``merge`` (correct, just without the
        conversion saving). The changeset must cover exactly
        ``n_slots`` (capacity adaptation needs the wide path)."""
        from ..ops.pallas_merge import (_cs_shape, model_fanin_split,
                                        pad_split_rows,
                                        split_guard_lanes, split_to_wide)
        self.drain_ingest()
        r, n = _cs_shape(scs)
        if n != self.n_slots:
            raise ValueError(
                f"pre-split changeset covers {n} slots but this "
                f"replica holds {self.n_slots}; use merge() (the wide "
                "path pads/refuses capacity mismatches)")
        if not self._use_pallas():
            return self.merge(split_to_wide(scs), node_ids)
        from ..ops.pallas_merge import MAX_NODE_ORDINAL
        if len(self._table) + len(node_ids) > MAX_NODE_ORDINAL:
            # int16 node lane ceiling (pre-intern upper bound; the
            # wide path routes >32k-ordinal tables to the XLA fold).
            return self.merge(split_to_wide(scs), node_ids)
        self.stats.merges += 1
        self._intern_ids(node_ids)
        # Ordinal remap happens IN-JIT (model_fanin_split's node_map
        # gather) — eager remap ops cost a dispatch round trip each.
        node_map = np.fromiter(
            (self._table.ordinal(nid) for nid in node_ids),
            np.int16, count=len(node_ids))
        # Shared small-delta chunk sizing (`_kernel_chunk_rows`): skip
        # the (expensive, eager) row padding whenever r fits one chunk.
        chunk = self._kernel_chunk_rows(r)
        if chunk < r:
            scs = pad_split_rows(scs, chunk)
        pipe = self._pipe
        if pipe is not None and not pipe.exact:
            # Coarse window: one fused dispatch, like the wide path —
            # else the zero-conversion interchange would be the SLOWER
            # pipelined route (the bookkeeping dispatches cost more
            # than the merge at gossip shapes).
            from ..ops.pallas_merge import pipelined_model_step_split
            wall_merge = self._wall_clock()
            wall_send = self._wall_clock()
            with merge_annotation("crdt_tpu.dense_merge",
                                  hlc=lambda: self._canonical_time):
                (new_store, new_canon, any_bad, overflow, drift,
                 val_ovf, first_idx, win_count, win, seen) = \
                    pipelined_model_step_split(
                        self._store, scs, jnp.asarray(node_map),
                        pipe.canonical, pipe.any_bad, pipe.overflow,
                        pipe.drift, pipe.val_overflow,
                        pipe.first_flag_idx,
                        jnp.int32(self._table.ordinal(self._node_id)),
                        jnp.int64(wall_merge), jnp.int64(wall_send),
                        jnp.int32(pipe.merges), chunk_rows=chunk,
                        interpret=self._executor == "pallas-interpret",
                        value_width=self._value_width)
            pipe.canonical = new_canon
            pipe.any_bad = any_bad
            pipe.overflow = overflow
            pipe.drift = drift
            pipe.val_overflow = val_ovf
            pipe.first_flag_idx = first_idx
            pipe.merges += 1
            self._store = self._postprocess_store(new_store)
            self.stats.add_seen_lazy(seen)
            self.stats.add_adopted_lazy(win_count)
            self._emit_merge_wins(new_store, win)
            return
        wall = self._wall_clock()
        with merge_annotation("crdt_tpu.dense_merge",
                              hlc=lambda: self._canonical_time):
            new_store, pres, seen, voverflow = model_fanin_split(
                self._store, scs, jnp.asarray(node_map),
                self._canonical_lt(),
                jnp.int32(self._table.ordinal(self._node_id)),
                jnp.int64(wall), chunk_rows=chunk,
                interpret=self._executor == "pallas-interpret",
                value_width=self._value_width)
        self.stats.add_seen_lazy(seen)
        res = self._pallas_result(pres)

        def wide_for_exact():
            # Failure path only: reconstruct wide lanes AND apply the
            # ordinal remap (the hot path remapped in-jit, so ``scs``
            # still carries peer ordinals).
            wide = split_to_wide(scs)
            table = jnp.asarray(node_map, jnp.int32)
            idx = jnp.clip(wide.node, 0, len(node_map) - 1)
            return wide._replace(
                node=jnp.where(wide.valid, table[idx], 0))

        self._finish_merge(
            new_store, res,
            voverflow if self._value_width == 32 else None, wall,
            wide_for_exact,
            guard_lanes=lambda: split_guard_lanes(
                scs.hi, scs.lo, scs.node, jnp.asarray(node_map)))

    # pack_since cache depth: a replica gossips a handful of peers with
    # (usually) one shared watermark frontier per store state; slots
    # beyond that are churn, not reuse. Depth is enforced by LRU
    # eviction (`_pack_cache_store`), so a peer churn storm — 100
    # distinct watermarks against one store state — cannot grow the
    # cache past this bound; evictions are counted in
    # ``crdt_tpu_pack_cache_evictions_total``.
    PACK_CACHE_SLOTS = 4

    def _resolve_sem_mode(self, sem_mode: str) -> str:
        if sem_mode not in ("auto", "include", "withhold"):
            raise ValueError(f"unknown sem_mode {sem_mode!r}")
        # "plain": untyped store — no lane to attach, nothing to
        # withhold (the seed wire form, whatever the caller asked).
        return "plain" if self._sem is None else (
            "withhold" if sem_mode == "auto" else sem_mode)

    def _pack_host_columns(self, mask: np.ndarray, lt: np.ndarray,
                           node: np.ndarray, val: np.ndarray,
                           tomb: np.ndarray,
                           resolved: str) -> PackedDelta:
        """Select the masked rows and land them in ONE arena
        (`ops.packing.pack_into_arena`) — the zero-copy pack tail
        shared by `pack_since` and `merge_and_repack`. The arena's
        views are the exact buffers `pack_rows` frames for the wire."""
        idx = np.nonzero(mask)[0]
        sem_src = None
        if resolved == "withhold":
            typed = self._sem[idx] != 0
            withheld = int(typed.sum())
            if withheld:
                from ..obs.registry import default_registry
                default_registry().counter(
                    "crdt_tpu_sync_semantics_downgrade_total",
                    "typed rows withheld from LWW-only wire forms "
                    "by direction").inc(withheld,
                                        direction="outbound",
                                        node=str(self._node_id))
                idx = idx[~typed]
        elif resolved == "include":
            sem_src = self._sem
        return pack_into_arena(idx, lt, node, val, tomb, sem=sem_src)

    def _pack_cache_store(self, key, out) -> None:
        """Insert a finished pack, LRU-evicting past PACK_CACHE_SLOTS
        with the eviction counter — churn storms stay bounded AND
        visible."""
        self._pack_cache[key] = out
        if len(self._pack_cache) > self.PACK_CACHE_SLOTS:
            from ..obs.registry import default_registry
            ev = default_registry().counter(
                "crdt_tpu_pack_cache_evictions_total",
                "pack_since cache entries LRU-evicted at the "
                "PACK_CACHE_SLOTS depth bound")
            while len(self._pack_cache) > self.PACK_CACHE_SLOTS:
                self._pack_cache.popitem(last=False)
                ev.inc(node=str(self._node_id))

    #: Slots per digest leaf (ops/digest.py, docs/ANTIENTROPY.md):
    #: the granularity at which the Merkle walk localizes divergence
    #: and the range pack re-ships rows. Mirrors
    #: `ops.digest.DEFAULT_LEAF_WIDTH`; both peers must agree (the
    #: walk checks geometry) — override in lockstep only.
    DIGEST_LEAF_WIDTH = 8

    def _digest_key(self):
        """Digest-cache key: clock head + semantics version + store
        generation. The generation term is what keeps a post-`gc_purge`
        /`compact` tree distinct — those replace the store WITHOUT
        advancing the canonical clock (docs/STORAGE.md)."""
        return (self._canonical_time.logical_time, self._sem_version,
                self._store_gen)

    def _digest_levels(self):
        """Device digest-tree levels (root-first) over the current
        store — overridden by the sharded model to fan per-shard
        subtrees in through `parallel/fanin.py`."""
        from ..ops.digest import digest_tree_device
        sem = self._sem_device() if self._sem is not None else None
        return digest_tree_device(self._store, sem,
                                  self.DIGEST_LEAF_WIDTH)

    def digest_tree(self):
        """Merkle anti-entropy digest tree (docs/ANTIENTROPY.md): a
        segment-tree of 64-bit digests over the replicated lanes,
        computed ON DEVICE in one jit-cached reduction and fetched with
        a single ``device_get``. Two replicas compare roots, walk only
        differing subtrees (O(log n) round trips over the ``digest``
        wire op), and re-ship just the divergent leaf ranges through
        ``pack_since(ranges=...)`` — cold-join traffic scales with
        divergence, not store size.

        Cached exactly like the pack cache, keyed on ``(clock,
        sem_version)``: every store replacement clears it through the
        ``_store`` setter and `set_semantics` migrations drop it, so
        an unchanged store recomputes (and dispatches) nothing.
        Lookups are counted in ``crdt_tpu_digest_cache_total``."""
        from ..obs.registry import default_registry
        from ..obs.trace import span
        from ..ops.digest import build_digest_tree
        # Drain BEFORE the key reads the canonical clock — same
        # aliasing hazard as pack_since.
        self.drain_ingest()
        key = self._digest_key()
        counter = default_registry().counter(
            "crdt_tpu_digest_cache_total",
            "digest_tree cache lookups by outcome")
        cached = self._digest_cache
        if cached is not None and cached[0] == key:
            counter.inc(outcome="hit", node=str(self._node_id))
            return cached[1]
        counter.inc(outcome="miss", node=str(self._node_id))
        with span("digest_tree", kind="digest",
                  hlc=lambda: self._canonical_time,
                  node=str(self._node_id)):
            tree = build_digest_tree(self.n_slots,
                                     self.DIGEST_LEAF_WIDTH,
                                     self._digest_levels())
        self._digest_cache = (key, tree)
        return tree

    def _normalize_ranges(self, ranges):
        """Validate/canonicalize a ``pack_since`` range mask: a
        sequence of half-open ``(lo, hi)`` slot spans -> sorted tuple
        with empty spans dropped. None means unrestricted."""
        if ranges is None:
            return None
        out = []
        for pair in ranges:
            lo, hi = pair
            lo, hi = int(lo), int(hi)
            if not 0 <= lo <= hi <= self.n_slots:
                raise ValueError(
                    f"pack range ({lo}, {hi}) out of bounds for "
                    f"{self.n_slots} slots")
            if lo < hi:
                out.append((lo, hi))
        return tuple(sorted(out))

    def _range_delta_mask(self, since: Optional[Hlc], ranges):
        """Device mask for `pack_since(ranges=...)`: the delta mask
        AND a union of slot spans. Span arrays pad to a power of two
        with empty ``(0, 0)`` spans so the jit cache sees O(log)
        distinct shapes across walks."""
        from ..ops.dense import dense_range_delta_mask
        k = max(1, len(ranges))
        pad = 1
        while pad < k:
            pad *= 2
        los = np.zeros(pad, np.int64)
        his = np.zeros(pad, np.int64)
        for i, (lo, hi) in enumerate(ranges):
            los[i] = lo
            his[i] = hi
        since_lt = 0 if since is None else since.logical_time
        return dense_range_delta_mask(self._store, jnp.int64(since_lt),
                                      jnp.asarray(los),
                                      jnp.asarray(his))

    def pack_since(self, since: Optional[Hlc] = None,
                   sem_mode: str = "auto", ranges=None
                   ) -> Tuple[PackedDelta, List[Any]]:
        """Outbound O(k) columnar delta: host lanes for the rows with
        ``modified >= since`` (inclusive, the `export_delta` bound) —
        the wire form `merge_packed` ingests. Unlike `export_delta` /
        `export_split_delta` this ships only MODIFIED rows (the
        `count_modified_since` mask), so steady-state gossip bytes are
        proportional to what changed, not to capacity.

        ``sem_mode`` is how the transport's capability negotiation
        reaches the pack (docs/WIRE.md): ``"include"`` attaches the
        uint8 ``sem`` tag lane (peer negotiated the ``semantics``
        hello cap); ``"withhold"`` drops non-LWW rows instead —
        withheld, never corrupted — counting them in
        ``crdt_tpu_sync_semantics_downgrade_total``; ``"auto"``
        (in-process callers) withholds only when the store actually
        holds typed slots. An all-LWW replica omits the lane under
        every mode — the legacy 5-lane frame stays byte-identical.

        ``ranges`` restricts the pack to a union of half-open
        ``(lo, hi)`` slot spans — the anti-entropy tail
        (docs/ANTIENTROPY.md): after a Merkle walk localizes
        divergence, only the divergent leaf ranges re-ship.
        ``ranges=((0, n_slots),)`` is bit-identical to the
        unrestricted pack.

        Results are cached keyed on ``(since, canonical, semantics
        version, mode, ranges)``; every store replacement — puts, deletes,
        merges, grow, ordinal remaps — clears the cache through the
        ``_store`` setter, and a `set_semantics` migration bumps the
        version (and clears outright), so a cached pack can never leak
        rows under stale tags. Hits/misses are counted in
        ``crdt_tpu_pack_cache_total``. The device lanes are copied to
        host here, so packing does NOT escape the store snapshot
        (later merges may still donate)."""
        from ..obs.registry import default_registry
        from ..obs.trace import span
        resolved = self._resolve_sem_mode(sem_mode)
        ranges = self._normalize_ranges(ranges)
        # Drain BEFORE the cache key reads the canonical: a flush
        # advances the clock AND replaces the store, so a key built
        # first would alias a pre-flush pack under a stale watermark.
        self.drain_ingest()
        key = (None if since is None else since.logical_time,
               self._canonical_time.logical_time,
               self._sem_version, self._store_gen, resolved, ranges)
        counter = default_registry().counter(
            "crdt_tpu_pack_cache_total",
            "pack_since cache lookups by outcome")
        cached = self._pack_cache.get(key)
        if cached is not None:
            self._pack_cache.move_to_end(key)
            counter.inc(outcome="hit", node=str(self._node_id))
            return cached
        counter.inc(outcome="miss", node=str(self._node_id))
        with span("pack_since", kind="pack",
                  hlc=lambda: self._canonical_time,
                  node=str(self._node_id)):
            mask = (self._delta_mask(since) if ranges is None
                    else self._range_delta_mask(since, ranges))
            # One batched device->host fetch; `modified` lanes are
            # local-only and never serialized (record.dart:28-31).
            mask, lt, node, val, tomb = jax.device_get(
                (mask, self._store.lt, self._store.node,
                 self._store.val, self._store.tomb))
            packed = self._pack_host_columns(mask, lt, node, val, tomb,
                                             resolved)
        out = (packed, self._table.ids())
        self._pack_cache_store(key, out)
        return out

    def merge_packed(self, packed: PackedDelta,
                     node_ids: Sequence[Any]) -> None:
        """Fan-in a `pack_since` delta: ``packed.node`` holds ordinals
        into ``node_ids`` (the peer's table order). Validation —
        aligned lanes, ordinal range, slot bounds, value width — runs
        BEFORE the first clock mutation, and duplicate slots collapse
        last-wins (`_last_wins_keep`), the same contract every other
        columnar ingest path honors. Cost is O(k) in the delta."""
        self._merge_packed_impl(packed, node_ids, None)

    def merge_and_repack(self, packed: PackedDelta,
                         node_ids: Sequence[Any],
                         since: Optional[Hlc] = None,
                         sem_mode: str = "auto"
                         ) -> Tuple[PackedDelta, List[Any]]:
        """`merge_packed` + `pack_since` fused into ONE device
        dispatch — the gossip relay op. The sparse join emits the next
        pack's delta mask from the same jitted program
        (`ops.dense.merge_repack_step`, donated store), so a relay
        round costs one dispatch instead of merge + cache-missed
        repack. Returns exactly what ``pack_since(since, sem_mode)``
        would return right after the merge, and seeds the pack cache
        under that key, so the NEXT watermark-aligned `pack_since`
        hits. Falls back to the two-step path whenever the fused
        kernel can't run (empty delta, wide join cutover, typed
        store)."""
        from ..obs.registry import default_registry
        resolved = self._resolve_sem_mode(sem_mode)
        since_lt = 0 if since is None else int(since.logical_time)
        mask = self._merge_packed_impl(packed, node_ids, since_lt)
        if mask is None:
            return self.pack_since(since, sem_mode)
        default_registry().counter(
            "crdt_tpu_fused_repack_total",
            "gossip relays served by the fused merge+repack "
            "dispatch").inc(node=str(self._node_id))
        key = (None if since is None else since.logical_time,
               self._canonical_time.logical_time,
               self._sem_version, self._store_gen, resolved, None)
        mask, lt, node, val, tomb = jax.device_get(
            (mask, self._store.lt, self._store.node,
             self._store.val, self._store.tomb))
        packed_out = self._pack_host_columns(mask, lt, node, val, tomb,
                                             resolved)
        out = (packed_out, self._table.ids())
        # Seed AFTER the merge assigned `_store` (the setter cleared
        # the cache), so the entry survives until the next store
        # replacement — exactly pack_since's lifetime rules.
        self._pack_cache_store(key, out)
        return out

    def _merge_packed_impl(self, packed: PackedDelta,
                           node_ids: Sequence[Any],
                           repack_since_lt: Optional[int]
                           ) -> Optional[jax.Array]:
        self._refuse_in_pipeline("merge_packed")  # host recv fold
        self.drain_ingest()
        slots = np.asarray(packed.slots)
        lt = np.asarray(packed.lt, np.int64)
        ni = np.asarray(packed.node)
        val = np.asarray(packed.val, np.int64)
        tomb = np.asarray(packed.tomb).astype(bool)
        sem = (None if getattr(packed, "sem", None) is None
               else np.asarray(packed.sem).astype(np.int8))
        k = len(slots)
        if not (len(lt) == len(ni) == len(val) == len(tomb) == k) \
                or (sem is not None and len(sem) != k):
            raise ValueError("packed delta lanes are ragged")
        if k == 0:
            self.merge_many([])
            return None
        if int(ni.min()) < 0 or int(ni.max()) >= len(node_ids):
            raise ValueError(
                f"packed node ordinal out of range for {len(node_ids)} "
                "wire node ids")
        keep = self._last_wins_keep(slots)
        if keep is not None:
            slots, lt, ni, val, tomb = (slots[keep], lt[keep], ni[keep],
                                        val[keep], tomb[keep])
            if sem is not None:
                sem = sem[keep]
            k = len(slots)
        self.stats.merges += 1
        self.stats.add_seen_lazy(k)
        self._check_slots(slots)
        if sem is not None:
            # Two replicas must never join one slot under two
            # different lattices: the peer's announced tag has to
            # match the local column exactly (LWW rows included), and
            # the rejection lands BEFORE the first clock mutation.
            mism = sem != self._sem_host()[slots]
            if bool(mism.any()):
                i = int(np.nonzero(mism)[0][0])
                raise ValueError(
                    f"semantics tag mismatch at slot {int(slots[i])}: "
                    f"peer sent tag {int(sem[i])}, local column holds "
                    f"{int(self._sem_host()[slots[i]])}; run the same "
                    "set_semantics migration on both replicas before "
                    "syncing")
        self._check_value_width(val)
        self._intern_ids(node_ids)
        node = self._table.encode(node_ids)[ni]
        return self._merge_validated(slots, lt, node, val, tomb,
                                     sem_ok=sem is not None,
                                     repack_since_lt=repack_since_lt)

    def _pipe_send_bump(self, wall: int) -> None:
        """The final crdt.dart:93 send bump, on device, flags
        accumulated (a device op can't raise; flush checks them)."""
        from ..ops.merge import send_step
        pipe = self._pipe
        new_lt, overflow, drift = send_step(pipe.canonical,
                                            jnp.int64(wall))
        pipe.canonical = new_lt
        # merges was already incremented for this merge; attribute the
        # send-bump flags to it (merges - 1 in 0-based window order).
        pipe.note(overflow | drift, idx=pipe.merges - 1)
        pipe.overflow = pipe.overflow | overflow
        pipe.drift = pipe.drift | drift


class ShardedDenseCrdt(DenseCrdt):
    """`DenseCrdt` with its key space sharded across a device mesh.

    Store lanes carry a ``NamedSharding`` over the mesh's key axis
    (replicated over the replica axis); ``merge``/``merge_many`` run
    the `crdt_tpu.parallel` fan-in — replica-axis lexicographic-max
    collectives over ICI, DCN across slices. Incoming changesets are
    padded with invalid rows up to a multiple of the mesh's replica
    dimension, then sharded ``(replica, key)``.

    On TPU meshes whose per-device key shards are tile-aligned (and
    under forced ``executor="pallas"``/``"pallas-interpret"``), the
    per-device reduce inside the collective step runs through the
    Mosaic batch kernel (`parallel.fanin.make_sharded_pallas_fanin`) —
    the same executor as the single-chip headline path — with the
    pmax/pmin/psum replica reduction combining the per-shard partial
    stores. ``executor="xla"`` forces the plain shard_map fold.
    Results are lane-exact across all three executors.

    Guard semantics: the collective flags are per-device (coarser than
    the sequential visit order); when one trips, the guards are
    recomputed exactly on the unsharded changeset (`_exact_guards`), so
    raised exceptions carry the same first-offender payload as the
    single-device model and per-device false positives never reject a
    merge the sequential order accepts.
    """

    def __init__(self, node_id: Any, n_slots: int, mesh,
                 wall_clock: Optional[Callable[[], int]] = None,
                 store: Optional[DenseStore] = None,
                 node_ids: Optional[Sequence[Any]] = None,
                 executor: str = "auto", value_width: int = 64):
        from ..parallel import KEY_AXIS, make_sharded_fanin, shard_store
        self._mesh = mesh
        self._sharded_step = make_sharded_fanin(mesh)
        self._sharded_pallas_step = None
        self._shard = lambda s: shard_store(s, mesh)
        if executor in ("pallas", "pallas-interpret"):
            # Per-shard alignment, validated eagerly like the base
            # model: each device's key shard feeds the kernel whole.
            from ..ops.pallas_merge import TILE
            k = mesh.shape[KEY_AXIS]
            if n_slots % k or (n_slots // k) % TILE:
                raise ValueError(
                    f"executor={executor!r} needs n_slots divisible by "
                    f"the mesh's {k} key shards with each shard a "
                    f"multiple of {TILE}; got n_slots={n_slots}")
        super().__init__(node_id, n_slots, wall_clock=wall_clock,
                         store=store, node_ids=node_ids,
                         executor=executor, value_width=value_width)
        self._store = self._shard(self._store)

    def _dispatch_fanin(self, cs: DenseChangeset, wall: int):
        from ..parallel import (make_sharded_pallas_fanin, replica_extent,
                                shard_changeset)
        if self._sem is not None:
            # Typed joins are elementwise — the shared typed fold runs
            # directly on the key-sharded lanes, no collective step.
            return self._typed_fanin(
                cs, self._canonical_lt(),
                jnp.int32(self._table.ordinal(self._node_id)), wall)
        # The replica dim shards over EVERY non-key mesh axis (just
        # "replica" on a flat mesh; ("slice", "replica") on a
        # multi-slice one).
        extent = replica_extent(self._mesh)
        if self._use_pallas_sharded():
            # Kernel path: each device's shard must walk in whole
            # chunk_rows groups, so the replica padding is coarser.
            if self._sharded_pallas_step is None:
                self._sharded_pallas_step = make_sharded_pallas_fanin(
                    self._mesh, chunk_rows=self.STREAM_CHUNK_ROWS,
                    interpret=self._executor == "pallas-interpret")
            cs = pad_replica_rows(cs, extent * self.STREAM_CHUNK_ROWS)
            cs = shard_changeset(cs, self._mesh)
            return self._sharded_pallas_step(
                self._store, cs,
                self._canonical_lt(),
                jnp.int32(self._table.ordinal(self._node_id)),
                jnp.int64(wall))
        cs = pad_replica_rows(cs, extent)
        cs = shard_changeset(cs, self._mesh)
        return self._sharded_step(
            self._store, cs,
            self._canonical_lt(),
            jnp.int32(self._table.ordinal(self._node_id)),
            jnp.int64(wall))

    def _use_pallas(self) -> bool:
        # False on purpose: merge_many's generic branch must keep its
        # seen-count / value-width device ops (the sharded collective
        # step doesn't fold them in). The kernel still runs — PER
        # SHARD, inside the shard_map body — when
        # `_use_pallas_sharded` routes `_dispatch_fanin` to
        # `make_sharded_pallas_fanin`.
        return False

    def _use_pallas_sharded(self) -> bool:
        """Route the sharded fan-in through the per-device Mosaic
        kernel? Forced by ``executor=`` ("pallas"/"pallas-interpret"
        on, "xla" off); "auto" takes the kernel when each device's key
        shard is tile-aligned, the node table fits the kernel's int16
        wire lane, and the backend is TPU."""
        if self._sem is not None:
            return False  # typed stores route through _typed_fanin
        from ..ops.pallas_merge import MAX_NODE_ORDINAL, TILE
        from ..parallel import KEY_AXIS
        if len(self._table) > MAX_NODE_ORDINAL:
            if self._executor in ("pallas", "pallas-interpret"):
                raise ValueError(
                    f"executor={self._executor!r} supports at most "
                    f"{MAX_NODE_ORDINAL} node ordinals; table holds "
                    f"{len(self._table)}")
            return False
        if self._executor == "xla":
            return False
        if self._executor in ("pallas", "pallas-interpret"):
            return True
        k = self._mesh.shape[KEY_AXIS]
        # Gate on the MESH's devices, not the process default: a CPU
        # validation mesh on a TPU host (or vice versa) must route by
        # where the store actually lives.
        return (self.n_slots % k == 0
                and (self.n_slots // k) % TILE == 0
                and self._mesh.devices.flat[0].platform == "tpu")

    # _exact_guards: inherited — ShardedFaninResult carries no
    # first_bad field, so the base recompute path handles the sharded
    # collectives' superset flags (see `crdt_tpu.parallel.fanin`).

    def _digest_levels(self):
        # Per-shard subtree leaves fan in along the key axis
        # (`parallel.make_sharded_digest`); falls back to the base
        # single-program reduction (still on device, GSPMD-sharded)
        # when leaf boundaries would straddle shards.
        from ..parallel import KEY_AXIS, make_sharded_digest
        k = self._mesh.shape[KEY_AXIS]
        if self.n_slots % k or (self.n_slots // k) % self.DIGEST_LEAF_WIDTH:
            return super()._digest_levels()
        has_sem = self._sem is not None
        fn = make_sharded_digest(self._mesh, self.DIGEST_LEAF_WIDTH,
                                 has_sem)
        if has_sem:
            return fn(self._store, self._sem_device())
        return fn(self._store)

    def _postprocess_store(self, store):
        # Sparse scatters land with XLA-chosen output sharding; pin the
        # key-axis NamedSharding back on. When every lane already
        # carries it (the in-jit with_sharding_constraint and the
        # shard_map programs both produce exactly this layout), skip
        # the 7-lane device_put round-trip outright — the sub-ms
        # dispatch path never pays for an identity re-shard.
        from ..parallel import store_sharding
        want = store_sharding(self._mesh)
        try:
            if all(getattr(lane, "sharding", None) == want
                   for lane in store):
                return store
        except Exception:  # non-addressable / tracer lanes: re-pin
            pass
        return self._shard(store)

    def _write_sharding(self):
        from ..parallel import store_sharding
        return store_sharding(self._mesh)

    def _commit_scatter(self, slots, lt, vals, tombs):
        # ONE shard_map program: every device takes its shard-local
        # rows of the (replicated) batch — no unsharded scatter, no
        # per-lane re-shard afterwards (the output is born on the
        # key-axis NamedSharding).
        from ..parallel import make_sharded_ingest
        d = len(slots)
        padded = 1 << max(d - 1, 1).bit_length()
        slot_l = np.full(padded, self.n_slots,
                         np.int32 if self.n_slots < 2 ** 31 - 1
                         else np.int64)
        lt_l = np.zeros(padded, np.int64)
        val_l = np.zeros(padded, np.int64)
        tomb_l = np.zeros(padded, bool)
        slot_l[:d] = slots
        lt_l[:d] = lt
        val_l[:d] = vals
        tomb_l[:d] = tombs
        step = make_sharded_ingest(self._mesh, self._donate_writes())
        return step(self._store, jnp.asarray(slot_l),
                    jnp.asarray(lt_l), jnp.asarray(val_l),
                    jnp.asarray(tomb_l),
                    jnp.int32(self._table.ordinal(self.node_id)))

    # put_batch/delete_batch need no override: the unstaged scatter
    # pins the key-axis sharding inside the jit (_write_sharding), and
    # _postprocess_store now recognizes that layout without a re-shard
    # dispatch; staged calls touch no device state until the
    # combiner's flush routes through _commit_scatter.

    def purge(self) -> None:
        super().purge()
        self._store = self._shard(self._store)

    def compact(self, ranges=None) -> np.ndarray:
        """Per-shard compaction inside ONE `shard_map`
        (`parallel.make_sharded_compact`): each device packs its own
        key shard to its local prefix, so the remap never crosses
        shard boundaries and the output is born on the key-axis
        sharding. Restricted ``ranges`` (or leaf-straddling shard
        geometry) fall back to the base single-program kernel, which
        is correct but may move rows across shards before
        `_postprocess_store` re-pins the layout."""
        from ..parallel import KEY_AXIS, make_sharded_compact
        k = self._mesh.shape[KEY_AXIS]
        if (ranges is not None or self.n_slots % k
                or (self.n_slots // k) % self.DIGEST_LEAF_WIDTH):
            return super().compact(ranges)
        self._refuse_in_pipeline("compact")
        self.drain_ingest()
        from ..ops.digest import build_digest_tree
        has_sem = self._sem is not None
        fn = make_sharded_compact(self._mesh, self.DIGEST_LEAF_WIDTH,
                                  has_sem, self._donate_writes())
        out = fn(self._store,
                 *((self._sem_device(),) if has_sem else ()))
        if has_sem:
            new_store, new_sem, translation, levels = out
        else:
            new_store, translation, levels = out
            new_sem = None
        translation = np.asarray(jax.device_get(translation))
        self._store = self._postprocess_store(new_store)
        self._store_escaped = False
        if new_sem is not None:
            sem_h = np.asarray(jax.device_get(new_sem)).astype(np.int8)
            self._sem = sem_h if sem_h.any() else None
            self._sem_dev = None
            self._sem_version += 1
        self._gc_purged = None
        self._gc_fence_dev = None
        tree = build_digest_tree(self.n_slots, self.DIGEST_LEAF_WIDTH,
                                 levels)
        self._digest_cache = (self._digest_key(), tree)
        from ..obs.registry import default_registry
        default_registry().counter(
            "crdt_tpu_compact_passes_total",
            "compact_remap dispatches").inc(node=str(self._node_id))
        return translation

    def grow(self, n_slots: int) -> None:
        from ..parallel import KEY_AXIS
        k = self._mesh.shape[KEY_AXIS]
        if n_slots % k:
            raise ValueError(
                f"n_slots={n_slots} not divisible by the mesh's "
                f"{k} key shards")
        if self._executor in ("pallas", "pallas-interpret"):
            from ..ops.pallas_merge import TILE
            if (n_slots // k) % TILE:
                raise ValueError(
                    f"executor={self._executor!r} needs each of the "
                    f"{k} key shards a multiple of {TILE}; got "
                    f"n_slots={n_slots}")
        if n_slots != self.n_slots:
            # jnp.concatenate on a key-sharded lane of this 2D mesh
            # folds the replicated 'replica' axis into a partial sum
            # (values double per replica) on current jax CPU meshes.
            # Pull the lanes off the mesh first; the base concat then
            # runs unsharded and _shard pins the grown layout back on.
            self.drain_ingest()
            self._store = DenseStore(
                *(jnp.asarray(np.asarray(lane)) for lane in self._store))
            if self._gc_fence_dev is not None:
                # Same off-mesh pull as the store lanes: the base
                # grow's concat must not run on a key-sharded mask.
                self._gc_fence_dev = jnp.asarray(
                    np.asarray(self._gc_fence_dev))
        super().grow(n_slots)
        self._store = self._shard(self._store)


def sync_dense(local: DenseCrdt, remote: DenseCrdt) -> None:
    """One anti-entropy round between two dense replicas
    (test/map_crdt_test.dart:273-279 semantics)."""
    time = local.canonical_time
    cs, ids = local.export_delta()
    remote.merge(cs, ids)
    cs, ids = remote.export_delta(since=time)
    local.merge(cs, ids)

"""Hybrid host-shadow / device-columnar CRDT backend — the drop-in
general-key TPU path.

Drop-in `Crdt` subclass (the reference's plugin pattern, README.md:39)
holding the record store as structure-of-arrays lanes twice over:

- **Host shadow** (numpy): the authoritative copy. Every per-record
  decision on the Python-object boundary — recv guard masks
  (vectorized running-max, hlc.dart:80-97), the LWW win compare
  (crdt.dart:83-84), record/JSON export — runs as batched numpy ops
  here. Rationale: on a remote-proxied accelerator every device→host
  fetch costs a full round trip that no record-dict batch size
  amortizes, so a backend that consults the device for win masks or
  guard flags is strictly slower than the scalar oracle at every
  record-dict shape. The shadow makes reads and merges fetch-free;
  numpy is the host's SIMD path (the same vectorization story as the
  device lanes, minus the transfer).
- **Device mirror** (`crdt_tpu.ops.merge.Store` in HBM): synced
  lazily — one async host→device push when a device consumer asks
  (`.store`) — for bulk array workflows: dense fan-in interop,
  sharded pipelines, kernel-side reductions. Merging through the
  record-dict API never blocks on it.

Wire ingest (`merge_json`) decodes straight to columns
(`crdt_json.decode_columns`: C batch HLC parse → packed int64 lane)
and merges without ever materializing `Record`/`Hlc` objects — the
host boundary the round-2 review found running at single-thread
CPython speed (per-record loops, `/root/reference/lib/src/
crdt.dart:77-109` surface) is now O(batch) numpy.

Division of labor with the reference semantics (crdt.dart:77-94):
clock absorption collapses to a running max; the duplicate-node /
drift guards evaluate against the exclusive cumulative max in payload
visit order (recv's fast path shields records the canonical clock
already dominates, hlc.dart:85); winners re-stamp ``modified`` with
the post-absorption canonical (crdt.dart:86-87); the final ``send``
bump runs on host (crdt.dart:93).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, TypeVar

import numpy as np

import jax.numpy as jnp

from ..crdt import Crdt
from .. import crdt_json
from ..hlc import (MAX_COUNTER, SHIFT, ClockDriftException,
                   DuplicateNodeException, Hlc)
from ..record import KeyDecoder, Record, ValueDecoder
from ..watch import ChangeHub, ChangeStream
from ..ops.merge import Store
from ..ops.packing import NodeTable
from ..utils.stats import MergeStats, merge_annotation

K = TypeVar("K")
V = TypeVar("V")

_MIN_CAPACITY = 8


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length() if n > 2 else max(n, _MIN_CAPACITY)


class _HostLanes:
    """The shadow store: six numpy lanes, grown geometrically."""

    __slots__ = ("lt", "node", "mod_lt", "mod_node", "occupied", "tomb")

    def __init__(self, capacity: int):
        self.lt = np.zeros(capacity, np.int64)
        self.node = np.zeros(capacity, np.int32)
        self.mod_lt = np.zeros(capacity, np.int64)
        self.mod_node = np.zeros(capacity, np.int32)
        self.occupied = np.zeros(capacity, bool)
        self.tomb = np.zeros(capacity, bool)

    @property
    def capacity(self) -> int:
        return self.lt.shape[0]

    def grow(self, capacity: int) -> None:
        pad = capacity - self.capacity
        if pad <= 0:
            return
        for name in self.__slots__:
            lane = getattr(self, name)
            setattr(self, name, np.concatenate(
                [lane, np.zeros(pad, lane.dtype)]))

    def remap_nodes(self, remap: np.ndarray) -> None:
        self.node = remap[self.node]
        self.mod_node = remap[self.mod_node]


class TpuMapCrdt(Crdt[K, V]):
    """LWW-map CRDT with host-shadow lanes + a lazy device mirror."""

    def __init__(self, node_id: Any,
                 seed: Optional[Dict[K, Record[V]]] = None,
                 wall_clock: Optional[Callable[[], int]] = None,
                 capacity: int = _MIN_CAPACITY):
        self._node_id = node_id
        self._table = NodeTable([node_id])
        self._lanes = _HostLanes(max(capacity, _MIN_CAPACITY))
        self._device: Optional[Store] = None   # None = stale mirror
        self._key_to_slot: Dict[K, int] = {}
        self._slot_keys: List[K] = []       # slot -> key, insertion order
        self._payload: List[Any] = []       # slot -> value (None = tombstone)
        self._hub = ChangeHub()
        self.stats = MergeStats().register(backend="TpuMapCrdt",
                                           node=str(node_id))
        if seed:
            # Seed lands before the canonical clock is derived, so
            # refresh_canonical_time absorbs it (map_crdt.dart:16-18 +
            # crdt.dart:31-33).
            self.put_records(dict(seed))
        super().__init__(wall_clock=wall_clock)

    # --- host bookkeeping ---

    @property
    def node_id(self) -> Any:
        return self._node_id

    @property
    def store(self) -> Store:
        """Device-columnar mirror of the shadow lanes (HBM
        structure-of-arrays, `ops.merge.Store`), synced on demand —
        the bridge into dense fan-in / sharded device workflows."""
        if self._device is None:
            l = self._lanes
            self._device = Store(
                lt=jnp.asarray(l.lt), node=jnp.asarray(l.node),
                mod_lt=jnp.asarray(l.mod_lt),
                mod_node=jnp.asarray(l.mod_node),
                occupied=jnp.asarray(l.occupied),
                tomb=jnp.asarray(l.tomb))
        return self._device

    def _my_ordinal(self) -> int:
        return self._table.ordinal(self._node_id)

    def _intern_nodes(self, node_ids) -> None:
        remap = self._table.intern(node_ids)
        if remap is not None:
            self._lanes.remap_nodes(remap)
            self._device = None

    def _ensure_slots(self, keys: Sequence[K]) -> np.ndarray:
        from .. import native
        codec = native.load()
        if codec is not None and isinstance(keys, list):
            # C batch get-or-insert: same dict, same slot assignment
            # order, minus ~1.8 s/1M of interpreter dispatch.
            buf, new_keys = codec.ensure_slots(
                self._key_to_slot, keys, len(self._slot_keys))
            slots = np.frombuffer(buf, np.int64)
            if new_keys:
                self._slot_keys.extend(new_keys)
                self._payload.extend([None] * len(new_keys))
        else:
            slots = np.empty(len(keys), dtype=np.int64)
            get = self._key_to_slot.get
            start = len(self._slot_keys)   # dict/lists in lockstep here
            pending = None   # key dict-inserted but not yet in the lists
            try:
                for i, key in enumerate(keys):
                    slot = get(key)
                    if slot is None:
                        slot = len(self._slot_keys)
                        pending = key
                        self._key_to_slot[key] = slot
                        self._slot_keys.append(key)
                        self._payload.append(None)
                        pending = None
                    slots[i] = slot
            except BaseException:
                # mid-batch failure (e.g. unhashable key): roll back
                # to the pre-batch state so dict and slot tables stay
                # consistent — the C path's contract. `pending` covers
                # the window where the dict holds a key the list tail
                # doesn't (yet).
                if pending is not None:
                    try:
                        del self._key_to_slot[pending]
                    except Exception:
                        pass  # the insert itself failed (unhashable)
                for key in self._slot_keys[start:]:
                    # pop (not del): the pending key may sit in both
                    # the list tail and the pending-cleanup above
                    self._key_to_slot.pop(key, None)
                del self._slot_keys[start:]
                del self._payload[start:]
                raise
        if len(self._slot_keys) > self._lanes.capacity:
            self._lanes.grow(_next_pow2(len(self._slot_keys)))
            self._device = None
        return slots

    def _ordinals(self, node_ids: Sequence[Any]) -> np.ndarray:
        """Vectorized id->ordinal encode (ids already interned)."""
        return self._table.encode(node_ids)

    # --- storage primitives (crdt.dart:140-169) ---

    def contains_key(self, key: K) -> bool:
        return key in self._key_to_slot

    def get_record(self, key: K) -> Optional[Record[V]]:
        slot = self._key_to_slot.get(key)
        if slot is None:
            return None
        l = self._lanes
        if not l.occupied[slot]:
            return None
        lt, mlt = int(l.lt[slot]), int(l.mod_lt[slot])
        return Record(
            Hlc._raw(lt >> SHIFT, lt & MAX_COUNTER,
                     self._table.id_of(int(l.node[slot]))),
            self._payload[slot],
            Hlc._raw(mlt >> SHIFT, mlt & MAX_COUNTER,
                     self._table.id_of(int(l.mod_node[slot]))))

    def put_record(self, key: K, record: Record[V]) -> None:
        self.put_records({key: record})

    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        if not record_map:
            return
        self.stats.puts += 1
        self.stats.records_put += len(record_map)
        keys = list(record_map.keys())
        records = list(record_map.values())
        m = len(records)
        from .. import native
        codec = native.load()
        if codec is not None:
            lt_buf, hlc_nodes, values, mlt_buf, mod_nodes = \
                codec.records_to_columns(records, True)
            lt = np.frombuffer(lt_buf, np.int64)
            mod_lt = np.frombuffer(mlt_buf, np.int64)
            tomb = np.frombuffer(codec.none_mask(values), bool)
        else:
            lt = np.fromiter((r.hlc.logical_time for r in records),
                             np.int64, count=m)
            mod_lt = np.fromiter(
                (r.modified.logical_time for r in records),
                np.int64, count=m)
            hlc_nodes = [r.hlc.node_id for r in records]
            mod_nodes = [r.modified.node_id for r in records]
            values = [r.value for r in records]
            tomb = np.fromiter((v is None for v in values), bool,
                               count=m)
        self._intern_nodes(hlc_nodes + mod_nodes)
        slots = self._ensure_slots(keys)
        l = self._lanes
        l.lt[slots] = lt
        l.node[slots] = self._ordinals(hlc_nodes)
        l.mod_lt[slots] = mod_lt
        l.mod_node[slots] = self._ordinals(mod_nodes)
        l.occupied[slots] = True
        l.tomb[slots] = tomb
        self._device = None
        self._scatter_all_and_emit(codec, slots, keys, values)

    def _scatter_all_and_emit(self, codec, slots, keys, values) -> None:
        """Whole-batch payload write (every entry lands — the put
        shapes, where there is no LWW filter) + batch event emission.
        The C scatter runs whether or not anyone is watching; events
        come afterwards, so a subscriber never de-vectorizes a bulk
        put (same contract as the merge path)."""
        payload = self._payload
        if codec is not None:
            codec.scatter_payload(payload, slots,
                                  np.arange(len(keys), dtype=np.int64),
                                  values)
        else:
            for i in range(len(keys)):
                payload[slots[i]] = values[i]
        if self._hub.active:
            key_to_slot = self._key_to_slot

            def get(k):
                slot = key_to_slot.get(k)
                # batch slots are exactly this put's keys; a key maps
                # into the batch iff its post-put payload position was
                # just written — putAll batches are dict-keyed, so
                # membership is equality of the stored slot
                if slot is None or not np.any(slots == slot):
                    return False, None
                return True, payload[slot]

            # crdtlint: disable=add-batch-unique-keys -- putAll batches are dict-keyed, so a key cannot repeat within the batch
            self._hub.add_batch(lambda: (list(keys), list(values)), get)

    def _delta_slots(self, modified_since: Optional[Hlc]) -> np.ndarray:
        """Occupied slot indices passing the INCLUSIVE ``modified``
        delta bound (map_crdt.dart:44-45) — the one delta-selection
        shared by ``record_map`` and the lane-direct ``to_json``."""
        n = len(self._slot_keys)
        if n == 0:
            return np.empty(0, np.int64)
        l = self._lanes
        mask = l.occupied[:n]
        if modified_since is not None:
            mask = mask & (l.mod_lt[:n] >= modified_since.logical_time)
        return np.nonzero(mask)[0]

    def put_all(self, values: Dict[K, Optional[V]]) -> None:
        """Batch put, ONE shared send-stamped HLC (crdt.dart:46-54) —
        written straight to the lanes: every record in the batch
        carries the identical (t, t) stamp pair, so there is nothing
        per-record to extract and no Record objects to build."""
        if not values:
            return  # no clock touch on an empty batch (crdt.dart:47-48)
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())
        t = self._canonical_time.logical_time
        self.stats.puts += 1
        self.stats.records_put += len(values)
        keys = list(values.keys())
        vals = list(values.values())
        self._intern_nodes([self._node_id])
        my_ord = self._my_ordinal()
        slots = self._ensure_slots(keys)
        from .. import native
        codec = native.load()
        l = self._lanes
        l.lt[slots] = t
        l.node[slots] = my_ord
        l.mod_lt[slots] = t
        l.mod_node[slots] = my_ord
        l.occupied[slots] = True
        if codec is not None:
            l.tomb[slots] = np.frombuffer(codec.none_mask(vals), bool)
        else:
            l.tomb[slots] = np.fromiter((v is None for v in vals),
                                        bool, count=len(vals))
        self._device = None
        self._scatter_all_and_emit(codec, slots, keys, vals)

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        idx = self._delta_slots(modified_since)
        if idx.size == 0:
            return {}
        l = self._lanes
        ids = np.array(self._table.ids(), object)
        keys = self._slot_keys
        payload = self._payload
        raw = Hlc._raw
        cols = (idx.tolist(),
                (l.lt[idx] >> SHIFT).tolist(),
                (l.lt[idx] & MAX_COUNTER).tolist(),
                ids[l.node[idx]],
                (l.mod_lt[idx] >> SHIFT).tolist(),
                (l.mod_lt[idx] & MAX_COUNTER).tolist(),
                ids[l.mod_node[idx]])
        return {
            keys[slot]: Record(raw(ms, c, nd), payload[slot],
                               raw(mms, mc, mnd))
            for slot, ms, c, nd, mms, mc, mnd in zip(*cols)
        }

    def to_json(self, modified_since: Optional[Hlc] = None,
                key_encoder=None, value_encoder=None) -> str:
        """Wire export (crdt.dart:124-135) straight from the shadow
        lanes: numpy delta mask, C-codec batch HLC formatting, one
        `json.dumps` — no Record/Hlc materialization. Byte-identical
        to the generic `record_map()` + `crdt_json.encode` path
        (same key stringification, same separators, same insertion
        order), which remains the fallback when the native codec is
        unavailable or a year falls outside the 1-9999 wire window."""
        from .. import native
        codec = native.load()
        if codec is None:
            return super().to_json(modified_since,
                                   key_encoder=key_encoder,
                                   value_encoder=value_encoder)
        l = self._lanes
        idx = self._delta_slots(modified_since)
        if idx.size == 0:
            return "{}"
        id_strs = np.array([str(i) for i in self._table.ids()], object)
        hlcs = codec.format_hlc_batch(
            (l.lt[idx] >> SHIFT).tolist(),
            (l.lt[idx] & MAX_COUNTER).tolist(),
            id_strs[l.node[idx]].tolist())
        if None in hlcs:
            # deferred item: an out-of-window year (the generic encoder
            # raises the reference's fail-fast message) or a non-UTF-8
            # node id (the generic encoder serializes it)
            return super().to_json(modified_since,
                                   key_encoder=key_encoder,
                                   value_encoder=value_encoder)
        keys = self._slot_keys
        payload = self._payload
        kenc = crdt_json.dart_str if key_encoder is None else key_encoder
        slot_list = idx.tolist()
        key_strs = [kenc(keys[s]) for s in slot_list]
        if value_encoder is None:
            values = [payload[s] for s in slot_list]
        else:
            values = [value_encoder(keys[s], payload[s])
                      for s in slot_list]
        dumps = crdt_json.compact_dumps
        if len(set(key_strs)) == len(key_strs):
            out = codec.format_wire(key_strs, hlcs, values, dumps)
            if out is not None:
                return out
        # colliding stringified keys collapse dict-style (last value,
        # first position) — same as the generic path
        obj = {k: {"hlc": h, "value": v}
               for k, h, v in zip(key_strs, hlcs, values)}
        return dumps(obj)

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        return self._hub.stream(key)

    def purge(self) -> None:
        self._lanes = _HostLanes(self._lanes.capacity)
        self._device = None
        self._key_to_slot.clear()
        self._slot_keys.clear()
        self._payload.clear()

    # --- overridden hot paths ---

    def refresh_canonical_time(self) -> None:
        """Vectorized canonical-clock rebuild: one max over the
        occupied lt lane (crdt.dart:114-121 'should be overridden')."""
        if not self._slot_keys:
            self._canonical_time = Hlc.from_logical_time(0, self._node_id)
            return
        l = self._lanes
        max_lt = int(np.max(np.where(l.occupied, l.lt, 0)))
        self._canonical_time = Hlc.from_logical_time(max_lt, self._node_id)

    def merge(self, remote_records: Dict[K, Record[V]]) -> None:
        """Batched lattice join (crdt.dart:77-94 semantics), fully
        vectorized on the shadow lanes."""
        wall = self._wall_clock()
        if not remote_records:
            # Dart still bumps the canonical clock on an empty merge
            # (crdt.dart:93 runs unconditionally). Second wall read keeps
            # clock-tick parity with the scalar oracle's merge.
            self._canonical_time = Hlc.send(self._canonical_time,
                                            millis=self._wall_clock())
            return
        records = list(remote_records.values())
        m = len(records)
        from .. import native
        codec = native.load()
        if codec is not None:
            lt_buf, nodes, values = codec.records_to_columns(
                records, False)
            lt = np.frombuffer(lt_buf, np.int64)
        else:
            lt = np.fromiter((r.hlc.logical_time for r in records),
                             np.int64, count=m)
            nodes = [r.hlc.node_id for r in records]
            values = [r.value for r in records]
        self._merge_columns(list(remote_records.keys()), lt, nodes,
                            values, wall)

    def merge_json(self, json_str: str,
                   key_decoder: Optional[KeyDecoder] = None,
                   value_decoder: Optional[ValueDecoder] = None) -> None:
        """Columnar wire ingest: C batch HLC parse -> packed lanes ->
        vectorized join, no per-record Record/Hlc objects
        (crdt.dart:100-109 surface at numpy speed)."""
        # Tick parity by construction: the decode-time `modified` stamp
        # read (which a merge immediately overwrites for winners) comes
        # from the SAME accounting helper the generic path uses, and
        # the empty payload routes through the real merge({}) — so this
        # override cannot drift from Crdt.merge_json's read count.
        self._decode_wall_millis()
        keys, lt, nodes, values = crdt_json.decode_columns(
            json_str, key_decoder=key_decoder, value_decoder=value_decoder)
        if not keys:
            self.merge({})
            return
        self._merge_columns(keys, lt, nodes, values, self._wall_clock())

    def _merge_columns(self, keys: List[K], lt: np.ndarray,
                       node_ids: List[Any], values: List[Any],
                       wall: int) -> None:
        """The shared merge core on columns. ``lt`` is int64[m] packed
        logical times aligned with ``keys``/``node_ids``/``values``."""
        m = len(keys)
        self.stats.merges += 1
        self.stats.records_seen += m
        self._intern_nodes(set(node_ids))
        node = self._ordinals(node_ids)
        my_ord = self._my_ordinal()
        canonical_lt = self._canonical_time.logical_time

        with merge_annotation("crdt_tpu.host_merge",
                              hlc=lambda: self._canonical_time):
            # --- stage 1: recv guards against the RUNNING canonical
            # (exclusive cummax — the fast path shields records the
            # clock already dominates, hlc.dart:85), in payload visit
            # order like the reference's sequential loop. One shared
            # fold with the other host backends (utils/host_guards.py).
            from ..utils.host_guards import recv_fold_columns
            fold = recv_fold_columns(lt, node == my_ord, canonical_lt,
                                     wall)
            if fold.bad_index is not None:
                # Canonical partially advanced to just before the
                # offender; store and host dicts untouched (guards
                # run before slot allocation — no rollback needed).
                self._canonical_time = Hlc.from_logical_time(
                    fold.canonical_at_fail, self._node_id)
                if fold.bad_is_dup:
                    raise DuplicateNodeException(str(self._node_id))
                raise ClockDriftException(
                    int(lt[fold.bad_index]) >> SHIFT, wall)
            new_canonical = fold.new_canonical

            # --- stage 2: vectorized LWW (strict: local wins ties).
            slots = self._ensure_slots(keys)
            l = self._lanes
            l_lt = l.lt[slots]
            l_node = l.node[slots]
            l_occ = l.occupied[slots]
            win = ~l_occ | (lt > l_lt) | ((lt == l_lt) & (node > l_node))

            # --- stage 3: re-stamp winners, scatter into the shadow.
            from .. import native
            codec = native.load()
            widx = slots[win]
            winners = np.nonzero(win)[0]
            l.lt[widx] = lt[win]
            l.node[widx] = node[win]
            l.mod_lt[widx] = new_canonical
            l.mod_node[widx] = my_ord
            l.occupied[widx] = True
            if codec is not None:
                l.tomb[widx] = np.frombuffer(
                    codec.none_mask(values), bool)[winners]
            else:
                l.tomb[widx] = np.fromiter(
                    (values[i] is None for i in winners),
                    bool, count=winners.size)
            self._device = None

        self.stats.records_adopted += int(winners.size)
        # Payload scatter stays on the C path whether or not anyone is
        # watching (a subscriber must not de-vectorize a 1M merge);
        # events are emitted afterwards from the winner indices.
        payload = self._payload
        if codec is not None:
            codec.scatter_payload(payload, slots, winners, values)
        else:
            for i in winners.tolist():
                payload[slots[i]] = values[i]
        if self._hub.active:
            win_list = winners.tolist()
            key_to_slot = self._key_to_slot

            def get(k):
                slot = key_to_slot.get(k)
                if slot is None:
                    return False, None
                # Exact winner membership: one vectorized scan of the
                # winner slots per keyed stream. (A mod_lt==canonical
                # stamp test is NOT sound here — a merge that doesn't
                # advance the clock leaves pre-merge records carrying
                # the same stamp, yielding spurious events.)
                if not bool(np.any(widx == slot)):
                    return False, None
                return True, payload[slot]

            if len(win_list) == m:   # every record won (fresh sync)
                # crdtlint: disable=add-batch-unique-keys -- merge payloads are dict-keyed record maps: keys cannot repeat
                self._hub.add_batch(lambda: (keys, values), get)
            else:
                # crdtlint: disable=add-batch-unique-keys -- merge payloads are dict-keyed record maps: keys cannot repeat
                self._hub.add_batch(
                    lambda: ([keys[i] for i in win_list],
                             [values[i] for i in win_list]), get)

        self._canonical_time = Hlc.send(
            Hlc.from_logical_time(new_canonical, self._node_id),
            millis=self._wall_clock())

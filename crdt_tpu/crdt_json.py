"""JSON wire codec (L3) — the replica-boundary format.

Matches the reference `lib/src/crdt_json.dart:1-38` byte-for-byte on the
golden strings in `test/map_crdt_test.dart:114-150`:

- ``encode``: ``{key: {"hlc": "<iso>-<hex4>-<node>", "value": v}}``,
  compact separators, insertion order preserved.
- ``decode``: stamps every incoming record's ``modified`` with
  ``max(canonical_time, Hlc.now(node_id))`` (crdt_json.dart:23-24).
- Keys stringified by default (crdt_json.dart:13) via :func:`dart_str`,
  which mirrors Dart's ``toString`` for the key types exercised by the
  reference tests (str, int, datetime).
"""

from __future__ import annotations

import json
from datetime import datetime
from typing import Any, Dict, Optional

from .hlc import Hlc
from .record import (KeyDecoder, KeyEncoder, NodeIdDecoder, Record,
                     ValueDecoder, ValueEncoder)


def dart_str(key: Any) -> str:
    """Default key stringification, matching Dart ``toString()`` for the
    reference's golden key types (map_crdt_test.dart:119-150)."""
    if isinstance(key, datetime):
        # Dart DateTime.toString(): 'YYYY-MM-DD HH:MM:SS.mmm' (+micros if set)
        base = (f"{key.year:04d}-{key.month:02d}-{key.day:02d} "
                f"{key.hour:02d}:{key.minute:02d}:{key.second:02d}")
        micros = key.microsecond
        if micros % 1000 == 0:
            return f"{base}.{micros // 1000:03d}"
        return f"{base}.{micros:06d}"
    if isinstance(key, bool):
        return "true" if key else "false"
    return str(key)


def _default(obj: Any) -> Any:
    to_json = getattr(obj, "to_json", None) or getattr(obj, "toJson", None)
    if callable(to_json):
        return to_json()
    raise TypeError(f"Object of type {type(obj).__name__} "
                    f"is not JSON serializable")


def encode(record_map: Dict[Any, Record],
           key_encoder: Optional[KeyEncoder] = None,
           value_encoder: Optional[ValueEncoder] = None) -> str:
    """Map of records -> wire JSON string (crdt_json.dart:8-17)."""
    obj = {
        (dart_str(key) if key_encoder is None else key_encoder(key)):
            record.to_json(key, value_encoder=value_encoder)
        for key, record in record_map.items()
    }
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False,
                      default=_default)


def decode(json_str: str, canonical_time: Hlc,
           key_decoder: Optional[KeyDecoder] = None,
           value_decoder: Optional[ValueDecoder] = None,
           node_id_decoder: Optional[NodeIdDecoder] = None,
           now_millis: Optional[int] = None) -> Dict[Any, Record]:
    """Wire JSON -> map of records, re-stamping ``modified`` with
    ``max(canonical, now)`` (crdt_json.dart:19-37).

    ``now_millis`` makes the wall-clock read injectable for tests.
    """
    now = Hlc.now(canonical_time.node_id, millis=now_millis)
    modified = canonical_time if canonical_time >= now else now
    raw = json.loads(json_str)
    return {
        (key if key_decoder is None else key_decoder(key)):
            Record.from_json(key, value, modified,
                             value_decoder=value_decoder,
                             node_id_decoder=node_id_decoder)
        for key, value in raw.items()
    }


class CrdtJson:
    """Namespace mirroring the reference's static class (crdt_json.dart:5)."""

    encode = staticmethod(encode)
    decode = staticmethod(decode)

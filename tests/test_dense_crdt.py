"""DenseCrdt: the device-resident integer-keyed model."""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu import ClockDriftException, DuplicateNodeException, Hlc
from crdt_tpu.checkpoint import load_dense, save_dense
from crdt_tpu.models.dense_crdt import DenseCrdt, sync_dense
from crdt_tpu.testing import FakeClock

N = 64
BASE = 1_700_000_000_000


def make(node="na", start=BASE):
    return DenseCrdt(node, N, wall_clock=FakeClock(start=start))


class TestLocalOps:
    def test_put_get(self):
        c = make()
        c.put_batch([1, 5], [10, 50])
        assert c.get(1) == 10
        assert c.get(5) == 50
        assert c.get(2) is None
        assert len(c) == 2

    def test_batch_shares_one_hlc(self):
        # putAll semantics: one send per batch (crdt.dart:50-52).
        c = make()
        c.put_batch([1, 5], [10, 50])
        assert int(c.store.lt[1]) == int(c.store.lt[5])

    def test_delete_tombstones(self):
        c = make()
        c.put_batch([3], [30])
        c.delete_batch([3])
        assert c.get(3) is None
        assert bool(c.store.occupied[3])   # never physically removed
        assert len(c) == 0

    def test_overwrite_advances_clock(self):
        c = make()
        c.put_batch([0], [1])
        t1 = int(c.store.lt[0])
        c.put_batch([0], [2])
        assert int(c.store.lt[0]) > t1
        assert c.get(0) == 2


class TestReplication:
    def test_two_replica_sync(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0, 1], [10, 11])
        b.put_batch([2], [22])
        sync_dense(a, b)
        for c in (a, b):
            assert c.get(0) == 10 and c.get(1) == 11 and c.get(2) == 22
        np.testing.assert_array_equal(np.asarray(a.store.val),
                                      np.asarray(b.store.val))

    def test_lww_conflict_newest_wins(self):
        a, b = make("na"), make("nb", BASE + 100)
        a.put_batch([0], [1])
        b.put_batch([0], [2])   # later wall clock
        sync_dense(a, b)
        assert a.get(0) == 2 and b.get(0) == 2

    def test_node_id_breaks_exact_tie(self):
        # Same wall millis on both replicas: larger node id wins
        # (hlc.dart:158-161).
        a, b = make("aa", BASE), make("zz", BASE)
        a.put_batch([0], [1])
        b.put_batch([0], [2])
        sync_dense(a, b)
        assert a.get(0) == 2 and b.get(0) == 2

    def test_tombstone_propagates(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0], [1])
        sync_dense(a, b)
        b.delete_batch([0])
        sync_dense(a, b)
        assert a.get(0) is None and b.get(0) is None

    def test_delta_export_inclusive(self):
        a = make()
        a.put_batch([0], [1])
        t = a.canonical_time
        cs, _ = a.export_delta(since=t)
        assert bool(cs.valid[0, 0])        # == bound kept (inclusive)
        a.put_batch([1], [2])
        cs, _ = a.export_delta(since=a.canonical_time)
        assert not bool(cs.valid[0, 0])
        assert bool(cs.valid[0, 1])

    def test_three_replica_relay(self):
        a, b, c = make("na"), make("nb", BASE + 3), make("nc", BASE + 7)
        a.put_batch([0], [10])
        c.put_batch([9], [90])
        sync_dense(a, b)
        sync_dense(b, c)
        sync_dense(a, b)
        for r in (a, b, c):
            assert r.get(0) == 10 and r.get(9) == 90

    def test_duplicate_node_raises(self):
        a, b = make("na"), make("na", BASE + 50)
        a.put_batch([0], [1])
        cs, ids = a.export_delta()
        with pytest.raises(DuplicateNodeException):
            b.merge(cs, ids)

    def test_drift_raises(self):
        a = make("na", BASE + 200_000)   # far-future writer
        a.put_batch([0], [1])
        b = make("nb", BASE)
        cs, ids = a.export_delta()
        with pytest.raises(ClockDriftException):
            b.merge(cs, ids)

    def test_node_remap_preserves_tiebreak(self):
        # A peer id sorting before existing ids shifts ordinals; stored
        # lanes must re-encode or tie-breaks invert.
        z = make("zz", BASE)
        z.put_batch([0], [1])
        a = make("aa", BASE)
        a.put_batch([0], [2])
        sync_dense(a, z)
        # equal logical times: zz > aa wins on both replicas
        assert a.get(0) == 1 and z.get(0) == 1


class TestMergeManyOrdinals:
    """Round-1 regression: merge_many interleaved peer interning with
    changeset encoding, so a later peer whose ids re-sorted the
    NodeTable left earlier-encoded changesets holding stale ordinals
    (spurious DuplicateNodeException, or silent writer mis-attribution
    and inverted tie-breaks). Ids must be interned as a union first."""

    def test_interleaved_interning_attribution(self):
        hub = DenseCrdt("m", N, wall_clock=FakeClock(start=BASE + 99))
        z = DenseCrdt("z", N, wall_clock=FakeClock(start=BASE))
        a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE + 3))
        z.put_batch([0], [10])
        a.put_batch([1], [20])
        # 'z' encodes first; interning 'a' then shifts 'z''s ordinal —
        # with the bug 'z''s rows carried hub's own ordinal ('m') and
        # raised DuplicateNodeException.
        hub.merge_many([z.export_delta(), a.export_delta()])
        assert hub.get(0) == 10 and hub.get(1) == 20
        assert hub._table.id_of(int(hub.store.node[0])) == "z"
        assert hub._table.id_of(int(hub.store.node[1])) == "a"

    def test_tiebreak_under_adversarial_intern_order(self):
        # Identical logical times on one slot: 'z' > 'a' must win the
        # node tie-break (hlc.dart:158-161) regardless of which peer's
        # changeset is encoded first.
        for order in (0, 1):
            hub = DenseCrdt("m", N, wall_clock=FakeClock(start=BASE + 99))
            z = DenseCrdt("z", N, wall_clock=FakeClock(start=BASE))
            a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE))
            z.put_batch([0], [10])
            a.put_batch([0], [20])
            deltas = [z.export_delta(), a.export_delta()]
            hub.merge_many(deltas if order == 0 else deltas[::-1])
            assert hub.get(0) == 10
            assert hub._table.id_of(int(hub.store.node[0])) == "z"

    def test_empty_merge_many_is_send_bump(self):
        # crdt.dart:93's final send bump runs even for an empty merge.
        c = make()
        t0 = c.canonical_time.logical_time
        c.merge_many([])
        assert c.canonical_time.logical_time > t0
        assert c.stats.merges == 1

    def test_slot_bounds_validated(self):
        c = make()
        with pytest.raises(IndexError):
            c.put_batch([N], [1])
        with pytest.raises(IndexError):
            c.delete_batch([-1])
        assert len(c) == 0


class TestDifferentialVsOracle:
    """DenseCrdt vs MapCrdt under equivalent random op schedules: the
    observable record state (event HLC + value + tombstone per key)
    must match exactly."""

    @pytest.mark.parametrize("seed", range(3))
    def test_fanin_matches_sequential_oracle(self, seed):
        import random
        from crdt_tpu import MapCrdt, Record

        rng = random.Random(seed)
        n_writers = 5
        dense_writers = []
        oracle_writers = []
        for i in range(n_writers):
            clock_d = FakeClock(start=BASE + i * 3)
            clock_o = FakeClock(start=BASE + i * 3)
            d = DenseCrdt(f"w{i}", N, wall_clock=clock_d)
            o = MapCrdt(f"w{i}", wall_clock=clock_o)
            for _ in range(rng.randrange(1, 4)):
                slots = sorted(rng.sample(range(N), rng.randrange(1, 9)))
                if rng.random() < 0.25:
                    d.delete_batch(slots)
                    o.put_all({s: None for s in slots})
                else:
                    vals = [rng.randrange(1000) for _ in slots]
                    d.put_batch(slots, vals)
                    o.put_all(dict(zip(slots, vals)))
            dense_writers.append(d)
            oracle_writers.append(o)

        hub = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
        hub.merge_many([w.export_delta() for w in dense_writers])

        oracle = MapCrdt("hub", wall_clock=FakeClock(start=BASE + 99))
        for o in oracle_writers:
            oracle.merge(o.record_map())

        recs = oracle.record_map()
        for slot in range(N):
            if slot not in recs:
                assert not bool(hub.store.occupied[slot])
                continue
            r = recs[slot]
            assert bool(hub.store.occupied[slot])
            assert int(hub.store.lt[slot]) == r.hlc.logical_time
            assert (hub._table.id_of(int(hub.store.node[slot]))
                    == r.hlc.node_id)
            assert bool(hub.store.tomb[slot]) == r.is_deleted
            if not r.is_deleted:
                assert int(hub.store.val[slot]) == r.value


class TestResume:
    def test_checkpoint_roundtrip(self, tmp_path):
        a = make()
        a.put_batch([0, 7], [5, 6])
        a.delete_batch([7])
        p = str(tmp_path / "dense.npz")
        save_dense(a.store, p)
        back = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE + 999),
                         store=load_dense(p))
        assert back.get(0) == 5 and back.get(7) is None
        # Resume rebuilt the clock from the lanes (crdt.dart:114-121).
        assert (back.canonical_time.logical_time
                == a.canonical_time.logical_time)

    def test_stats(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0, 1], [1, 2])
        sync_dense(a, b)
        assert b.stats.merges == 1
        assert b.stats.records_adopted == 2

"""Anti-entropy sync rounds (C10) — the reference's replication protocol
as a library utility.

The reference keeps the sync round in its tests
(`test/map_crdt_test.dart:273-279`): capture the local canonical time,
full-push to the remote, then delta-pull everything the remote modified
at-or-after that time (inclusive bound, map_crdt.dart:44-45). Three-node
convergence through an intermediary relies on merged records being
re-stamped with the relay's ``modified`` time (crdt.dart:87) — the
relay's deltas then include records it learned from others.

Two transports:

- :func:`sync` — in-process record maps (replicas share a process, the
  reference's own test topology).
- :func:`sync_json` — the JSON wire format (crdt_json.dart), what
  crosses a real replica boundary; transport remains the application's
  job (example/crdt_example.dart:21-25).
"""

from __future__ import annotations

from typing import Optional

from .crdt import Crdt
from .record import (KeyDecoder, KeyEncoder, ValueDecoder, ValueEncoder)


def sync(local: Crdt, remote: Crdt) -> None:
    """One push/pull anti-entropy round between two in-process replicas.

    After a round in each direction (or one round plus a later reverse
    round) the two replicas converge; N replicas converge through any
    connected gossip topology."""
    time = local.canonical_time
    remote.merge(local.record_map())
    local.merge(remote.record_map(modified_since=time))


def sync_json(local: Crdt, remote: Crdt,
              key_encoder: Optional[KeyEncoder] = None,
              value_encoder: Optional[ValueEncoder] = None,
              key_decoder: Optional[KeyDecoder] = None,
              value_decoder: Optional[ValueDecoder] = None) -> None:
    """The same round over the JSON wire format — full-state push, then
    delta pull keyed on the pre-push canonical time (crdt.dart:124-135).
    """
    time = local.canonical_time
    remote.merge_json(local.to_json(key_encoder=key_encoder,
                                    value_encoder=value_encoder),
                      key_decoder=key_decoder,
                      value_decoder=value_decoder)
    local.merge_json(remote.to_json(modified_since=time,
                                    key_encoder=key_encoder,
                                    value_encoder=value_encoder),
                     key_decoder=key_decoder,
                     value_decoder=value_decoder)

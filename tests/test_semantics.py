"""The CRDT type zoo end to end: registry contracts, typed dense
model behavior, the semantics-parametrized conformance suite on the
single-device and sharded models, keyed delegation, wire downgrade
behavior against LWW-only peers, and a mixed-semantics three-replica
gossip round under fault injection (docs/TYPES.md)."""

import random
import time

import numpy as np
import pytest

import jax

from crdt_tpu import semantics
from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt
from crdt_tpu.models.keyed_dense import KeyedDenseCrdt
from crdt_tpu.obs.registry import default_registry
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.semantics import (GCOUNTER, LWW, MVREG, ORSET, PNCOUNTER,
                                SemanticsSpec, all_semantics, by_tag,
                                get_semantics, names)
from crdt_tpu.testing import FakeClock, SemanticsConformance

N = 64
BASE = 1_700_000_000_000


# ---------------------------------------------------------------- registry


def test_registry_ships_five_semantics_with_unique_tags():
    specs = all_semantics()
    assert [s.name for s in specs] == ["lww", "gcounter", "pncounter",
                                       "orset", "mvreg"]
    assert [s.tag for s in specs] == [0, 1, 2, 3, 4]
    assert LWW.tag == 0   # untyped store must be all-zeros
    for s in specs:
        assert get_semantics(s.name) is s
        assert by_tag(s.tag) is s


def test_registry_rejects_duplicate_name_and_tag():
    with pytest.raises(ValueError, match="already registered"):
        semantics.register(SemanticsSpec(
            name="lww", tag=99, doc="", encode=int, decode=int,
            law_val=lambda lt, node: lt))
    with pytest.raises(ValueError, match="already registered"):
        semantics.register(SemanticsSpec(
            name="fresh", tag=0, doc="", encode=int, decode=int,
            law_val=lambda lt, node: lt))
    with pytest.raises(KeyError, match="unknown semantics"):
        get_semantics("nope")
    with pytest.raises(KeyError, match="unknown semantics tag"):
        by_tag(77)


def test_registry_codecs_round_trip():
    assert PNCOUNTER.decode(PNCOUNTER.encode(-5)) == -5
    assert PNCOUNTER.decode(PNCOUNTER.encode(9)) == 9
    assert GCOUNTER.encode(3) == 3
    with pytest.raises(ValueError, match="non-negative"):
        GCOUNTER.encode(-1)
    assert ORSET.decode(ORSET.encode([1, 5])) == frozenset({1, 5})
    with pytest.raises(ValueError, match="universe"):
        ORSET.encode([16])
    assert MVREG.decode(MVREG.encode(7)) == (7,)
    with pytest.raises(ValueError, match="16-bit"):
        MVREG.encode(0)


def test_registry_drives_law_and_audit_target_generation():
    # zero hand-listed targets: every registered semantics surfaces in
    # BOTH analysis target lists, by name
    from crdt_tpu.analysis.jaxpr_audit import (builtin_targets
                                               as audit_builtins)
    from crdt_tpu.analysis.lattice_laws import (builtin_targets
                                                as law_builtins)
    law_names = {t.name for t in law_builtins()}
    audit_names = {t.name for t in audit_builtins(include_sharded=False)}
    for s in all_semantics():
        assert f"semantics.{s.name}.typed_wire_join" in law_names
        assert f"semantics.{s.name}.typed_wire_join" in audit_names
    assert "semantics.typed_sparse_join_step" in audit_names
    assert "semantics.typed_fanin_step" in audit_names


def test_cli_completeness_gate_flags_spec_missing_targets(monkeypatch):
    from crdt_tpu.analysis.cli import _registry_completeness
    bare = SemanticsSpec(name="bare", tag=9, doc="", encode=int,
                         decode=int, law_val=lambda lt, node: lt)
    monkeypatch.setattr(semantics, "all_semantics",
                        lambda: all_semantics() + [bare])
    rules = sorted(f.rule for f in _registry_completeness())
    assert rules == ["semantics-missing-audit-target",
                     "semantics-missing-law-target"]
    for f in _registry_completeness():
        assert "'bare'" in f.message
    # and the shipped registry is complete
    monkeypatch.undo()
    assert _registry_completeness() == []


def test_broken_counter_fixture_fails_law_search():
    from crdt_tpu.analysis.lattice_laws import run_laws
    from tests.fixtures.broken_counter import LAW_TARGETS
    findings = run_laws(LAW_TARGETS, seeds=(0, 1, 2))
    rules = {f.rule for f in findings}
    # increment-instead-of-max breaks every law the harness checks
    assert {"law-idempotence", "law-commutativity"} <= rules
    for f in findings:
        assert "violating input (seed=" in (f.detail or "")


# ---------------------------------------------------- typed model surface


def _dense(node_id, **kw):
    kw.setdefault("wall_clock", FakeClock(start=BASE))
    return DenseCrdt(node_id, N, **kw)


def test_set_semantics_accepts_spec_name_and_tag():
    c = _dense("a")
    c.set_semantics([0], PNCOUNTER)
    c.set_semantics([1], "orset")
    c.set_semantics([2], 4)
    assert c.semantics_of(0) is PNCOUNTER
    assert c.semantics_of(1) is ORSET
    assert c.semantics_of(2) is MVREG
    assert c.semantics_of(3) is LWW
    # resetting every typed slot back to lww collapses the column
    c.set_semantics([0, 1, 2], "lww")
    assert not c._has_typed


def test_counter_ops_and_overflow_guards():
    c = _dense("a")
    c.set_semantics([0], "gcounter")
    c.set_semantics([1], "pncounter")
    assert c.counter_add(0, 5) == 5
    assert c.counter_add(0, 2) == 7
    assert c.counter_value(0) == 7
    with pytest.raises(ValueError, match="grow-only"):
        c.counter_add(0, -1)
    assert c.counter_add(1, 10) == 10
    assert c.counter_add(1, -25) == -15
    assert c.counter_value(1) == -15
    with pytest.raises((ValueError, OverflowError)):
        c.counter_add(1, 1 << 40)
    with pytest.raises((TypeError, ValueError)):
        c.counter_add(2, 1)   # slot 2 is lww, not a counter


def test_orset_add_remove_and_saturation():
    c = _dense("a")
    c.set_semantics([0], "orset")
    assert c.orset_add(0, 3) == frozenset({3})
    assert c.orset_add(0, 3) == frozenset({3})   # no-op re-add
    assert c.orset_add(0, 7) == frozenset({3, 7})
    assert c.orset_remove(0, 3) == frozenset({7})
    assert c.orset_remove(0, 3) == frozenset({7})  # no-op re-remove
    assert c.orset_members(0) == frozenset({7})
    with pytest.raises(ValueError, match="universe"):
        c.orset_add(0, 16)
    for _ in range(6):   # causal length climbs 2 per add/remove pair
        c.orset_add(0, 3)
        c.orset_remove(0, 3)
    c.orset_add(0, 3)    # length 15: the final odd state
    with pytest.raises(OverflowError, match="satur"):
        c.orset_remove(0, 3)


def test_mvreg_put_get():
    c = _dense("a")
    c.set_semantics([0], "mvreg")
    assert c.mvreg_get(0) == ()
    c.mvreg_put(0, 42)
    assert c.mvreg_get(0) == (42,)
    c.mvreg_put(0, 7)    # strictly newer lt: replaces, not unions
    assert c.mvreg_get(0) == (7,)


def test_mvreg_equal_lt_union_across_replicas():
    # identical frozen clocks => equal lt stamps => true concurrency:
    # the register must UNION, newest-first, instead of dropping one
    a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("b", N, wall_clock=FakeClock(start=BASE))
    for c in (a, b):
        c.set_semantics([0], "mvreg")
    a.mvreg_put(0, 5)
    b.mvreg_put(0, 9)
    cs, ids = b.export_delta()
    a.merge(cs, ids)
    assert a.mvreg_get(0) == (9, 5)


def test_ingest_window_accumulates_counter_rmw():
    c = _dense("a")
    c.set_semantics([0], "pncounter")
    with c.ingest():
        for _ in range(5):
            c.counter_add(0, 2)
        assert c.counter_value(0) == 10   # read-your-writes overlay
    assert c.counter_value(0) == 10


def test_grow_preserves_semantics_column():
    c = _dense("a")
    c.set_semantics([0], "gcounter")
    c.counter_add(0, 3)
    c.grow(N * 2)
    assert c.semantics_of(0) is GCOUNTER
    assert c.semantics_of(N) is LWW
    assert c.counter_value(0) == 3


def test_merge_packed_rejects_semantics_tag_mismatch():
    a = _dense("a")
    b = _dense("b")
    a.set_semantics([0], "pncounter")
    b.set_semantics([0], "gcounter")
    a.counter_add(0, 4)
    pk, ids = a.pack_since(None, sem_mode="include")
    before = b.canonical_time
    with pytest.raises(ValueError, match="semantics tag mismatch"):
        b.merge_packed(pk, ids)
    # rejected BEFORE any clock mutation: the replica is untouched
    assert b.canonical_time == before
    assert b.counter_value(0) == 0


# --------------------------------------- conformance suite instantiations


class TestDenseSemanticsConformance(SemanticsConformance):
    def make_dense(self, node_id):
        return DenseCrdt(node_id, self.n_slots,
                         wall_clock=FakeClock(start=BASE))


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
class TestShardedSemanticsConformance(SemanticsConformance):
    def make_dense(self, node_id):
        return ShardedDenseCrdt(node_id, self.n_slots,
                                make_fanin_mesh(2, 4),
                                wall_clock=FakeClock(start=BASE))


# ------------------------------------------------------- keyed delegation


def test_keyed_typed_ops_delegate_through_interning():
    kc = KeyedDenseCrdt(_dense("a"))
    kc.set_semantics(["hits", "balance"], "pncounter")
    kc.set_semantics(["tags"], "orset")
    kc.set_semantics(["owner"], "mvreg")
    assert kc.semantics_of("hits") is PNCOUNTER
    assert kc.semantics_of("never-seen") is LWW
    assert kc.counter_add("hits", 3) == 3
    assert kc.counter_add("balance", -2) == -2
    assert kc.counter_value("hits") == 3
    assert kc.orset_add("tags", 1) == frozenset({1})
    assert kc.orset_remove("tags", 1) == frozenset()
    assert kc.orset_members("tags") == frozenset()
    kc.mvreg_put("owner", 77)
    assert kc.mvreg_get("owner") == (77,)
    # plain lww keys keep working beside typed ones
    kc.put("plain", 5)
    assert kc.get("plain") == 5


# -------------------------------------------------- mixed-semantics gossip


@pytest.mark.net
def test_mixed_semantics_three_replica_gossip_under_faults():
    """Three DenseCrdt replicas (packed wire, semantics negotiated)
    gossiping through fault proxies: after a faulty phase and a
    passthrough settle phase, every replica agrees on every typed AND
    untyped slot."""
    from crdt_tpu import BreakerPolicy, GossipNode, RetryPolicy
    from crdt_tpu.testing import FaultProxy, FaultSchedule

    retry = RetryPolicy(max_attempts=4, base_delay=0.001,
                        max_delay=0.01)
    breaker = BreakerPolicy(failure_threshold=4, reset_timeout=0.02)
    nodes = {}
    for name in ("a", "b", "c"):
        crdt = DenseCrdt(name, N, wall_clock=FakeClock(start=BASE))
        crdt.set_semantics([0], "gcounter")
        crdt.set_semantics([1], "pncounter")
        crdt.set_semantics([2], "orset")
        crdt.set_semantics([3], "mvreg")
        nodes[name] = GossipNode(crdt, retry=retry, breaker=breaker,
                                 rng=random.Random(11))
    proxies = {}
    try:
        for i, (name, node) in enumerate(sorted(nodes.items())):
            node.start()
            proxies[name] = FaultProxy(
                node.host, node.port,
                FaultSchedule(seed=i, rate=0.3,
                              max_delay=0.005)).start()
        for name, node in nodes.items():
            for other, proxy in proxies.items():
                if other != name:
                    node.add_peer(other, proxy.host, proxy.port)
        # one writer per counter slot pair would need 6 slots; the
        # shared counter slots instead get a SINGLE writer ("a") —
        # the dense counter contract — while every replica writes the
        # multi-writer types
        with nodes["a"].lock:
            nodes["a"].crdt.counter_add(0, 5)
            nodes["a"].crdt.counter_add(1, -3)
        for i, (name, node) in enumerate(sorted(nodes.items())):
            with node.lock:
                node.crdt.orset_add(2, i)
                node.crdt.mvreg_put(3, 10 + i)
                node.crdt.put_batch([8 + i], [100 + i])
        # faulty phase: best effort
        for _ in range(6):
            for node in nodes.values():
                node.run_round()
        # settle phase: passthrough, loop until all-ok sweeps
        for proxy in proxies.values():
            proxy.passthrough = True
        deadline = time.monotonic() + 30
        while True:
            ok = all(v in ("ok",)
                     for node in nodes.values()
                     for v in node.run_round().values())
            if ok:
                # one more full sweep so late writes propagate through
                # the relay replica as well
                done = all(v == "ok"
                           for node in nodes.values()
                           for v in node.run_round().values())
                if done:
                    break
            assert time.monotonic() < deadline, "mesh did not settle"
        crdts = [n.crdt for n in nodes.values()]
        base = crdts[0]
        for other in crdts[1:]:
            assert other.counter_value(0) == base.counter_value(0) == 5
            assert other.counter_value(1) == base.counter_value(1) == -3
            assert (other.orset_members(2) == base.orset_members(2)
                    == frozenset({0, 1, 2}))
            assert other.mvreg_get(3) == base.mvreg_get(3)
            for slot in (8, 9, 10):
                assert other.get(slot) == base.get(slot)
        assert base.mvreg_get(3) != ()
        for i, slot in enumerate((8, 9, 10)):
            assert base.get(slot) == 100 + i
    finally:
        for proxy in proxies.values():
            proxy.stop()
        for node in nodes.values():
            node.stop()


# --------------------------------------------- wire downgrade (LWW peers)


def test_pack_withhold_keeps_typed_rows_home_and_counts_them():
    a = _dense("a")
    a.set_semantics([0], "gcounter")
    a.counter_add(0, 4)
    a.put_batch([5], [50])
    counter = default_registry().counter(
        "crdt_tpu_sync_semantics_downgrade_total")
    before = counter.value(direction="outbound", node="a")

    pk, ids = a.pack_since(None)   # auto => withhold on a typed store
    assert pk.sem is None
    assert list(pk.slots) == [5]   # typed row withheld, lww row ships
    assert counter.value(direction="outbound", node="a") == before + 1

    # include mode ships the tag lane for negotiated peers
    pk2, _ = a.pack_since(None, sem_mode="include")
    assert pk2.sem is not None and set(pk2.slots) == {0, 5}


def test_inbound_sem_less_frame_withholds_typed_slots():
    # a pre-semantics peer's 5-lane frame may still name typed slots;
    # the receiver must withhold those rows (not corrupt the lattice)
    # and land the rest
    a = _dense("a")
    b = _dense("b")
    b.set_semantics([0], "pncounter")
    a.put_batch([0, 5], [123, 50])   # slot 0 is typed ONLY at b
    pk, ids = a.pack_since(None)     # a is untyped: plain 5-lane pack
    assert pk.sem is None
    b.merge_packed(pk, ids)
    assert b.counter_value(0) == 0   # withheld, not reinterpreted
    assert b.get(5) == 50            # untyped row landed

"""SLO-driven elastic repartitioning (ROADMAP item 1, closing the
loop PR 13 opened).

`split_hot` grows the federation and `merge_cold` shrinks it; this
module is the controller that decides WHEN, from evidence the system
already collects — per-partition committed-row rates (the serve ack
pipeline's volume signal), queue depth and shed counters, replica
health, and `evaluate_slo` verdicts (obs/fleet.py). A fleet tracking
diurnal traffic must shrink as safely as it grows, and "safely" is a
list of disciplines, each of which this controller enforces and
crdtlint's `scale-decision-unfenced` rule holds it to:

- **Hysteresis**: pressure must persist for ``hysteresis_ticks``
  consecutive observations before a decision fires — one hot tick is
  a burst, not a trend.
- **Cooldown**: after a completed change the controller holds for
  ``cooldown_s`` so the fleet (and the rate estimator, which resets
  across topology changes) can settle before the next decision.
- **One change in flight**: `_apply` refuses while a prior change is
  still running; topology changes are serialized end to end.
- **Epoch fencing**: every decision carries the table epoch its
  evidence was read under, and `_apply` re-checks it immediately
  before acting — a stale observation must never retire an arc a
  concurrent change just made hot.
- **Floor/ceiling**: hard partition-count bounds; the controller
  never merges below ``min_partitions`` or splits above
  ``max_partitions``.
- **Degraded mode**: when any SLO input is unmeasured (no rate
  baseline yet, no ack samples) or a group has no live primary, ALL
  scaling freezes — in particular the controller never merges, since
  unmeasured ≠ safe to shrink and a primaryless group's load is
  invisible.

Decisions are counted in
``crdt_tpu_autoscale_decisions_total{action,reason}`` and executed
inside trace spans, so a scale action is auditable after the fact
(docs/FEDERATION.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Autoscaler"]

# The controller's default ack target: the measured 14.6 ms SERVE_r01
# envelope (obs/fleet.py SERVE_ACK_ENVELOPE_S — kept literal here so
# importing the controller never drags the fleet module in). This
# budget is only gateable because evaluate_slo reads the ack p99 from
# the mergeable quantile sketch (obs/sketch.py): the log2 histogram's
# nearest stable boundary is 31.3 ms, more than 2x the envelope, and
# a bucket ceiling between the two is unmeasured — not a verdict.
_ACK_P99_BUDGET_S = 0.0146


def _metrics():
    from .obs.registry import default_registry
    reg = default_registry()
    return {
        "decisions": reg.counter(
            "crdt_tpu_autoscale_decisions_total",
            "autoscaler decisions by action and reason"),
        "degraded": reg.gauge(
            "crdt_tpu_autoscale_degraded",
            "1 while scaling is frozen (unmeasured SLO inputs or a "
            "primaryless group)"),
    }


class Autoscaler:
    """Closed-loop controller driving `FederatedTier.split_hot` /
    `merge_cold` against an SLO target.

    ``split_rows_per_s`` is the per-partition committed-row rate above
    which the hottest partition is split; ``merge_rows_per_s`` the
    rate below which — when EVERY partition is that cold — the coldest
    is merged away (all-cold is deliberately conservative: a fleet
    with one busy partition and three idle ones keeps its headroom).

    The controller owns NO locks of its own (the empty
    `_CRDTLINT_LOCK_ORDER` below is the checked statement of that):
    split/merge serialization lives entirely in the federation's
    ``_control``, so a wedged scale action can never also wedge the
    poller.

    An ack-p99 SLO breach (`evaluate_slo`) counts as split pressure
    even below the rate threshold. ``slo_probe`` injects the verdict
    source (tests; the default evaluates the in-process registry).

    Run as a daemon (``start``/``stop`` or context manager) ticking
    every ``interval`` seconds, or drive ``tick()`` by hand.
    """

    _CRDTLINT_LOCK_ORDER: tuple = ()

    def __init__(self, fed, *, interval: float = 0.25,
                 min_partitions: int = 1, max_partitions: int = 8,
                 split_rows_per_s: float = 400.0,
                 merge_rows_per_s: float = 50.0,
                 hysteresis_ticks: int = 3, cooldown_s: float = 2.0,
                 ack_p99_budget_s: float = _ACK_P99_BUDGET_S,
                 slo_probe: Optional[Callable[[], dict]] = None):
        if min_partitions < 1:
            raise ValueError(
                f"min_partitions must be >= 1; got {min_partitions}")
        if max_partitions < min_partitions:
            raise ValueError(
                f"max_partitions {max_partitions} < min_partitions "
                f"{min_partitions}")
        self.fed = fed
        self.interval = float(interval)
        self.min_partitions = int(min_partitions)
        self.max_partitions = int(max_partitions)
        self.split_rows_per_s = float(split_rows_per_s)
        self.merge_rows_per_s = float(merge_rows_per_s)
        self.hysteresis_ticks = int(hysteresis_ticks)
        self.cooldown_s = float(cooldown_s)
        self.ack_p99_budget_s = float(ack_p99_budget_s)
        self._slo_probe = slo_probe
        # In-flight fence: the action currently executing, or None.
        # Written only by the thread driving tick(); read by _apply's
        # fence check.
        self._inflight: Optional[str] = None
        self._last_change_t: Optional[float] = None
        self._streak = {"split": 0, "merge": 0}
        # Rate baseline: previous (rows list, monotonic time); reset
        # to None across topology changes so rates are never computed
        # across a partition-list reshape.
        self._prev_rows: Optional[List[int]] = None
        self._prev_t: Optional[float] = None
        self.last_action: Optional[dict] = None
        self.decisions: List[dict] = []   # bounded audit log
        # Flight-recorder edge detector: a bundle is dumped when the
        # SLO verdict FLIPS to failing, not on every failing tick.
        self._last_slo_ok: Optional[bool] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # A failed tick must never kill the control loop; the
                # decision counter records failures and the next tick
                # re-observes from scratch.
                pass
            self._stop.wait(self.interval)

    # --- observe ---

    def _default_slo(self) -> dict:
        from .obs.fleet import evaluate_slo
        from .obs.registry import default_registry
        return evaluate_slo({"local": default_registry().snapshot()},
                            ack_p99_budget_s=self.ack_p99_budget_s)

    def observe(self) -> dict:
        """One evidence snapshot, stamped with the table epoch it was
        read under (the fence `_apply` later re-checks). Rates are
        None — unmeasured — on the first tick and on the first tick
        after any topology change, which is exactly when the degraded
        freeze must hold scaling still."""
        fed = self.fed
        table = fed.table
        epoch = None if table is None else table.epoch
        tiers = list(fed.tiers)
        groups = list(fed.groups)
        rows: List[int] = []
        depth = 0
        shed = 0
        for t in tiers:
            wc = t._wc
            rows.append(0 if wc is None else int(wc.rows_committed))
            depth += len(t._q)
            shed += int(t.shed_count)
        primaryless: List[int] = []
        for i, g in enumerate(groups):
            if g is None:
                if i < len(tiers) and tiers[i].killed:
                    primaryless.append(i)
                continue
            m = g.primary
            if m is None or m.tier is None or m.tier.killed:
                primaryless.append(i)
        now = time.monotonic()
        rates: Optional[List[float]] = None
        if self._prev_rows is not None \
                and len(self._prev_rows) == len(rows) \
                and self._prev_t is not None and now > self._prev_t:
            dt = now - self._prev_t
            rates = [max(0.0, (b - a) / dt)
                     for a, b in zip(self._prev_rows, rows)]
        self._prev_rows = rows
        self._prev_t = now
        slo = (self._slo_probe() if self._slo_probe is not None
               else self._default_slo())
        slo_ok = slo.get("ok") if isinstance(slo, dict) else None
        if slo_ok is False and self._last_slo_ok is not False:
            # SLO just flipped to failing: capture forensics NOW,
            # while the trace ring and sketches still hold the bad
            # window (obs/recorder.py; never let it wedge the tick).
            try:
                from .obs.recorder import default_recorder
                default_recorder().trigger(
                    "slo_failing",
                    {"slo": slo, "epoch": epoch,
                     "partitions": len(tiers)})
            except Exception:
                pass
        self._last_slo_ok = slo_ok
        return {"epoch": epoch, "partitions": len(tiers),
                "rows": rows, "rates": rates, "queue_depth": depth,
                "shed": shed, "primaryless": primaryless,
                "slo": slo, "t": now}

    # --- decide ---

    def degraded_reason(self, obs: dict) -> Optional[str]:
        """Why scaling is frozen, or None when every input is
        measured and every group has a live primary. Unmeasured ≠
        safe to shrink: a controller that merges on a rate it never
        observed is guessing with someone's arc."""
        if obs["epoch"] is None:
            return "no-table"
        if obs["primaryless"]:
            return "primaryless-group"
        if obs["rates"] is None:
            return "unmeasured-rate"
        slo = obs.get("slo")
        checks = slo.get("checks", {}) if isinstance(slo, dict) else {}
        ack = checks.get("ack_p99_s", {})
        if ack.get("ok") is None:
            return "unmeasured-slo"
        return None

    def decide(self, obs: dict) -> dict:
        """Pure decision from one observation: ``{"action":
        "split"|"merge"|"hold", "reason", "src", "epoch"}``. Carries
        the observation's epoch so `_apply` can fence it. Mutates the
        hysteresis streaks (consecutive pressured observations)."""
        dec: Dict[str, Any] = {"action": "hold", "reason": "steady",
                               "src": None, "epoch": obs["epoch"]}
        frozen = self.degraded_reason(obs)
        if frozen is not None:
            self._streak["split"] = self._streak["merge"] = 0
            dec["reason"] = f"degraded:{frozen}"
            return dec
        rates = obs["rates"]
        hot = max(range(len(rates)), key=lambda i: rates[i])
        cold = min(range(len(rates)), key=lambda i: rates[i])
        slo = obs["slo"]
        ack = slo.get("checks", {}).get("ack_p99_s", {}) \
            if isinstance(slo, dict) else {}
        up = (rates[hot] >= self.split_rows_per_s
              or ack.get("ok") is False)
        # All-cold, not just coldest-cold: one busy partition keeps
        # the whole fleet's headroom.
        down = (not up) and max(rates) < self.merge_rows_per_s
        self._streak["split"] = self._streak["split"] + 1 if up else 0
        self._streak["merge"] = self._streak["merge"] + 1 if down \
            else 0
        if self._last_change_t is not None and \
                obs["t"] - self._last_change_t < self.cooldown_s:
            dec["reason"] = "cooldown"
            return dec
        if up:
            if obs["partitions"] >= self.max_partitions:
                dec["reason"] = "ceiling"
            elif self._streak["split"] < self.hysteresis_ticks:
                dec["reason"] = "hysteresis"
            else:
                dec.update(action="split", src=hot,
                           reason=("slo-breach"
                                   if ack.get("ok") is False
                                   else "hot-rate"))
            return dec
        if down:
            if obs["partitions"] <= self.min_partitions:
                dec["reason"] = "floor"
            elif self._streak["merge"] < self.hysteresis_ticks:
                dec["reason"] = "hysteresis"
            else:
                dec.update(action="merge", src=cold,
                           reason="all-cold")
        return dec

    # --- act ---

    def _note(self, action: str, reason: str,
              epoch: Optional[int]) -> dict:
        rec = {"action": action, "reason": reason, "epoch": epoch,
               "t": time.monotonic()}
        self.decisions.append(rec)
        del self.decisions[:-256]
        _metrics()["decisions"].inc(action=action, reason=reason)
        return rec

    def _apply(self, dec: dict) -> bool:
        """Execute one split/merge decision behind both fences: no
        other change in flight, and the table epoch still the one the
        evidence was read under. Returns True when the change
        completed."""
        fed = self.fed
        if self._inflight is not None:
            self._note(dec["action"], "fence:inflight", dec["epoch"])
            return False
        table = fed.table
        if table is None or table.epoch != dec["epoch"]:
            # The topology moved between observe and act: the
            # evidence (per-partition rates, the src index itself) is
            # stale. Drop the decision; the next tick re-observes.
            self._note(dec["action"], "fence:stale-epoch",
                       dec["epoch"])
            return False
        from .obs.trace import span
        self._inflight = dec["action"]
        try:
            with span(f"autoscale.{dec['action']}", kind="autoscale",
                      reason=dec["reason"], epoch=dec["epoch"],
                      src=dec["src"]):
                if dec["action"] == "split":
                    fed.split_hot(src=dec["src"])
                else:
                    fed.merge_cold(src=dec["src"])
        except (ConnectionError, OSError, ValueError, RuntimeError,
                IndexError):
            self._note(dec["action"], "failed", dec["epoch"])
            return False
        finally:
            self._inflight = None
        self._last_change_t = time.monotonic()
        self._streak["split"] = self._streak["merge"] = 0
        # Partition list reshaped: the rate baseline is meaningless
        # until two post-change observations exist.
        self._prev_rows = None
        self._prev_t = None
        self.last_action = self._note(dec["action"], dec["reason"],
                                      dec["epoch"])
        return True

    def tick(self) -> dict:
        """One observe → decide → (maybe) act cycle. Returns the
        decision record."""
        obs = self.observe()
        dec = self.decide(obs)
        m = _metrics()
        m["degraded"].set(
            1.0 if dec["reason"].startswith("degraded:") else 0.0)
        if dec["action"] == "hold":
            self._note("hold", dec["reason"], dec["epoch"])
            return dec
        dec["applied"] = self._apply(dec)
        return dec

"""Pure-host routing tests: `RoutingTable` construction/evolution and
the `PartitionRouter.check` admission matrix. No sockets, no device —
this is the half of federation that must be exhaustively cheap to
test, since every serve-loop keyspace op rides through `check`."""

import pytest

from crdt_tpu.routing import PROXY, PartitionRouter, RoutingTable

A, B, C = "10.0.0.1:7001", "10.0.0.2:7002", "10.0.0.3:7003"


def _coverage_ok(table):
    cursor = 0
    for lo, hi, owner in table.ranges:
        assert lo == cursor and hi > lo and owner
        cursor = hi
    assert cursor == table.n_slots


class TestBuild:
    def test_covers_keyspace_exactly(self):
        t = RoutingTable.build(1 << 12, [A, B, C])
        _coverage_ok(t)
        assert t.epoch == 0

    def test_every_owner_holds_slots(self):
        t = RoutingTable.build(1 << 12, [A, B, C])
        for owner in (A, B, C):
            assert t.slots_of(owner) > 0
        assert sum(t.slots_of(o) for o in t.owners()) == t.n_slots

    def test_deterministic_across_calls_and_owner_order(self):
        # Token placement is FNV-1a, not builtin hash(): the same
        # owner set must yield the same table in every process.
        t1 = RoutingTable.build(1 << 12, [A, B, C])
        t2 = RoutingTable.build(1 << 12, [A, B, C])
        assert t1 == t2

    def test_adding_owner_moves_only_bisected_arcs(self):
        # The consistent-hashing stability property: slots that do not
        # move to the new owner keep their old owner.
        small = RoutingTable.build(1 << 12, [A, B])
        grown = RoutingTable.build(1 << 12, [A, B, C])
        moved = stayed = 0
        for slot in range(0, 1 << 12, 7):
            before, after = small.owner_of(slot), grown.owner_of(slot)
            if after == C:
                moved += 1
            else:
                assert after == before
                stayed += 1
        assert moved > 0 and stayed > 0

    def test_more_vnodes_smooths_shares(self):
        t = RoutingTable.build(1 << 14, [A, B, C, "10.0.0.4:7004"],
                               vnodes=64)
        shares = [t.slots_of(o) for o in t.owners()]
        assert max(shares) < 2.5 * (t.n_slots / len(shares))

    def test_tiny_ring_falls_back_to_even(self):
        # 4 slots can starve an owner of arcs; build() must still hand
        # every started tier something to own.
        t = RoutingTable.build(4, [A, B, C])
        assert set(t.owners()) == {A, B, C}

    def test_even_split(self):
        t = RoutingTable.even(100, [A, B, C])
        _coverage_ok(t)
        assert t.ranges == ((0, 33, A), (33, 66, B), (66, 100, C))

    def test_malformed_tables_rejected(self):
        with pytest.raises(ValueError):
            RoutingTable(8, 0, [(0, 4, A), (5, 8, B)])   # gap
        with pytest.raises(ValueError):
            RoutingTable(8, 0, [(0, 4, A), (3, 8, B)])   # overlap
        with pytest.raises(ValueError):
            RoutingTable(8, 0, [(0, 4, A)])              # short
        with pytest.raises(ValueError):
            RoutingTable(8, 0, [(0, 8, "")])             # empty owner
        with pytest.raises(ValueError):
            RoutingTable.build(8, [])


class TestEvolution:
    def test_split_bumps_epoch_and_reassigns_tail(self):
        t = RoutingTable.even(100, [A, B])
        lo, hi = t.ranges_of(A)[0]
        s = t.split(lo, (lo + hi) // 2, C)
        assert s.epoch == t.epoch + 1
        _coverage_ok(s)
        assert s.owner_of(lo) == A
        assert s.owner_of((lo + hi) // 2) == C
        assert s.owner_of(hi - 1) == C
        assert s.owner_of(hi) == B
        # The source table is immutable.
        assert t.owner_of(hi - 1) == A and t.epoch == 0

    def test_split_point_must_be_interior(self):
        t = RoutingTable.even(100, [A, B])
        with pytest.raises(ValueError):
            t.split(0, 0, C)
        with pytest.raises(ValueError):
            t.split(0, 50, C)    # == range hi
        with pytest.raises(ValueError):
            t.split(7, 20, C)    # no range starts at 7

    def test_merge_hands_every_arc_to_the_survivor(self):
        t = RoutingTable.even(100, [A, B, C])
        m = t.merge(B, A)
        assert m.epoch == t.epoch + 1
        _coverage_ok(m)
        assert B not in m.owners()
        assert set(m.owners()) == {A, C}
        # Every slot B owned now resolves to A; everyone else is
        # untouched.
        for slot in range(t.n_slots):
            was = t.owner_of(slot)
            assert m.owner_of(slot) == (A if was == B else was)
        # Adjacent arcs with the same owner coalesce: total range
        # count shrinks or holds, never grows.
        assert len(m.ranges) <= len(t.ranges)
        # The source table is immutable.
        assert t.owner_of(t.ranges_of(B)[0][0]) == B and t.epoch == 0

    def test_merge_refuses_degenerate_requests(self):
        t = RoutingTable.even(100, [A, B])
        with pytest.raises(ValueError):
            t.merge(A, A)                   # self-merge
        with pytest.raises(ValueError):
            t.merge(C, A)                   # retiree owns nothing
        with pytest.raises(ValueError):
            t.merge(A, C)                   # recipient must already own
                                            # arcs (reassign handles
                                            # promotion flips)

    def test_newest_is_a_join(self):
        t0 = RoutingTable.even(100, [A, B])
        t1 = t0.split(0, 25, C)
        assert RoutingTable.newest(t0, t1) is t1
        assert RoutingTable.newest(t1, t0) is t1
        assert RoutingTable.newest(None, t0) is t0
        assert RoutingTable.newest(t0, None) is t0
        assert RoutingTable.newest(None, None) is None

    def test_json_round_trip(self):
        t = RoutingTable.build(1 << 10, [A, B, C])
        obj = t.to_json()
        assert RoutingTable.from_json(obj) == t
        # And survives an actual wire trip through json.
        import json
        assert RoutingTable.from_json(json.loads(json.dumps(obj))) == t


class TestRouterCheck:
    def _router(self):
        t = RoutingTable.even(100, [A, B])
        r = PartitionRouter()
        r.bind(A, t)
        return r, t

    def test_owned_fresh_admits(self):
        r, t = self._router()
        assert r.check(10, t.epoch, fed_ok=True) is None
        assert r.check(10, None, fed_ok=True) is None   # epoch-less op

    def test_foreign_federated_gets_moved(self):
        r, t = self._router()
        verdict = r.check(60, t.epoch, fed_ok=True)
        assert verdict["code"] == "moved"
        assert verdict["owner"] == B
        assert verdict["epoch"] == t.epoch
        assert verdict["ok"] is False

    def test_foreign_legacy_session_proxies(self):
        # A session that never negotiated the federation cap cannot
        # parse `moved`; the serve loop must forward on its behalf.
        r, t = self._router()
        assert r.check(60, None, fed_ok=False) is PROXY

    def test_stale_epoch_refused_even_when_owned(self):
        # The refusal that stops a client from racing a live split:
        # its next write is blocked until it refetches the table.
        r, t = self._router()
        t1 = t.split(0, 25, C)
        assert r.install(t1)
        verdict = r.check(10, t.epoch, fed_ok=True)
        assert verdict["code"] == "moved"
        assert verdict["owner"] == A      # owner did not change...
        assert verdict["epoch"] == t1.epoch  # ...but the epoch did

    def test_install_refuses_rollback(self):
        r, t = self._router()
        t1 = t.split(0, 25, C)
        assert r.install(t1)
        assert not r.install(t)            # out-of-order gossip
        assert r.table is t1
        assert r.epoch == t1.epoch

    def test_unbound_router_admits_everything(self):
        r = PartitionRouter()
        assert r.check(0, None, fed_ok=False) is None
        assert r.check(99, 123, fed_ok=True) is None

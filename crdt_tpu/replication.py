"""Replica groups: health-checked failover, write-concern acks, and
zero-loss crash recovery (docs/REPLICATION.md).

The federated front door (routing.py / federation.py) scales OUT —
each partition owns an arc of the keyspace — but a partition is one
process, and one crash loses every acked write on its arc. This
module backs a partition with N replicas and makes three promises:

1. **Write concern** — the primary's flush tick resolves client acks
   only after :class:`Replicator` confirms the tick's `PackedDelta`
   on ``ack_replicas`` followers (serve.py's barrier, held to shape
   by the crdtlint ``ack-before-replicate`` rule). A primary crash
   then loses zero ACKED writes: everything acked is already a
   durable lattice row somewhere that can win the election.
2. **Failover** — a monitor thread heartbeats every member over the
   wire (the serve ``heartbeat`` op, which deliberately rides the
   replica executor so a wedged replica lane reads as dead). A
   primary that misses ``lease_misses`` consecutive beats is
   declared dead; the most-caught-up live follower (highest durable
   HLC head, digest-root then name as tie-breaks) is promoted; the
   routing table flips via `RoutingTable.reassign` (epoch + 1) and
   clients recover through the existing ``moved`` retry machinery.
3. **Rejoin** — a restarted replica builds a FRESH store (the crash
   image is never reused), catches up with a merkle frontier walk
   against the current primary, and re-enters as a follower.

Why this is NOT consensus: every replicated payload is an idempotent
lattice join, so replay, duplication, and even a brief dual-primary
window (an old primary serving out its lease while the new one is
already elected) cannot diverge the store — both sides' writes merge.
What the machinery guarantees is the ACK contract: an acked write
survives any single crash, and a fenced primary (expired lease, or a
write-concern barrier it cannot clear) answers the retryable ``busy``
code instead of acking writes it cannot back. CRDT convergence turns
the usual consensus problem into a routing/liveness problem — the
survey framing in PAPER.md, taken literally.

Role is ROUTING, not a mode switch: every member runs the same
`ServeTier` with a `PartitionRouter` whose table names the primary as
owner of the whole arc. A client write landing on a follower answers
``moved`` through the normal admission gate; promotion is just a
table flip. Gossip reuse: per-follower `CircuitBreaker` /
`BreakerPolicy` (gossip.py) keep a dead follower from adding its
timeout to every barrier.
"""

from __future__ import annotations

import socket
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, List, Optional, Tuple

from .analysis.concurrency import make_lock
from .gossip import BreakerPolicy, CircuitBreaker
from .hlc import Hlc
from .net import (PeerConnection, SyncError, SyncProtocolError,
                  WireTally, _pack_for_peer, recv_frame,
                  send_bytes_frame, send_frame,
                  sync_merkle_over_conn)
from .routing import PartitionRouter, RoutingTable
from .serve import ServeTier

__all__ = ["Replicator", "ReplicaGroup"]


def _split_addr(addr: str) -> Tuple[str, int]:
    host, _, port = str(addr).rpartition(":")
    return host, int(port)


class _Follower:
    """Primary-side view of one follower: pooled session, pack
    watermark, durable head, breaker, and the in-flight ship (a
    follower still chewing a previous barrier's pack is skipped, not
    waited on — one slow follower must not serialize ticks)."""

    __slots__ = ("name", "addr", "conn", "mark", "durable", "breaker",
                 "inflight")

    def __init__(self, name: str, addr: str, timeout: float):
        self.name = name
        self.addr = addr
        host, port = _split_addr(addr)
        self.conn = PeerConnection(
            host, port, timeout=timeout,
            want_caps=("zlib", "packed", "semantics", "replication"))
        self.mark: Optional[Hlc] = None
        self.durable: Optional[str] = None
        self.breaker = CircuitBreaker(
            BreakerPolicy(failure_threshold=3, reset_timeout=1.0),
            name=name)
        self.inflight = None


class Replicator:
    """The write-concern half of a primary: ship each tick's pack to
    every follower, report success once ``ack_replicas`` confirmed.

    ``barrier()`` runs on the tier's replica executor immediately
    after the tick's commit (same thread), so the pack taken under
    the tier lock necessarily contains the tick. Shipping fans out on
    a private pool; per-follower packs are `pack_since(mark)` where
    ``mark`` is that follower's last confirmed head — usually equal
    across followers, so the store's pack cache collapses N packs
    into one device dispatch.
    """

    # Checked by analysis/concurrency.py: membership mutations may
    # hold `_lock` while reading the tier's store lock, never the
    # reverse — barrier() runs lock-free on the tier's executor.
    _CRDTLINT_LOCK_ORDER = ("_lock", ("tier.lock", "ServeTier.lock"))

    def __init__(self, tier: ServeTier, followers: Dict[str, str],
                 ack_replicas: int = 1, timeout: float = 0.25,
                 group: str = "g0"):
        self.tier = tier
        self.ack_replicas = int(ack_replicas)
        self.timeout = float(timeout)
        self.group = str(group)
        self.tally = WireTally()
        self._lock = make_lock("Replicator._lock", 20)  # membership
        self._followers: Dict[str, _Follower] = {
            str(name): _Follower(str(name), str(addr), self.timeout)
            for name, addr in followers.items()}
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self._followers) + 1),
            thread_name_prefix="replicate")
        from .obs.registry import default_registry
        reg = default_registry()
        self._m_acks = reg.counter(
            "crdt_tpu_replication_acks_total",
            "write-concern barrier follower confirmations by outcome")
        self._m_barrier = reg.histogram(
            "crdt_tpu_replication_barrier_seconds",
            "flush-tick write-concern barrier wall time")
        # Sketch twin: relative-error barrier quantiles for the fleet
        # roll-up (obs/sketch.py; docs/OBSERVABILITY.md).
        self._m_barrier_sketch = reg.sketch(
            "crdt_tpu_replication_barrier_seconds_sketch",
            "flush-tick write-concern barrier wall time, "
            "relative-error quantile sketch")

    # --- membership (monitor thread) ---

    def add_follower(self, name: str, addr: str) -> None:
        with self._lock:
            self._followers[str(name)] = _Follower(
                str(name), str(addr), self.timeout)

    def drop_follower(self, name: str) -> None:
        with self._lock:
            f = self._followers.pop(str(name), None)
        if f is not None:
            f.conn.close()

    def status(self) -> dict:
        with self._lock:
            followers = list(self._followers.values())
        return {f.name: {"addr": f.addr, "durable": f.durable,
                         "breaker": f.breaker.state}
                for f in followers}

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            followers = list(self._followers.values())
            self._followers.clear()
        for f in followers:
            try:
                f.conn.close()
            except Exception:
                pass

    # --- the barrier (tier replica executor thread) ---

    def barrier(self) -> Tuple[bool, str]:
        """Confirm the just-committed tick on ``ack_replicas``
        followers. Returns ``(ok, detail)``; a miss maps to the
        retryable ``busy`` ack in serve.py — the local commit stands
        (idempotent join, converges later), but the CLIENT retries
        until an ack backed by the group lands."""
        need = self.ack_replicas
        if need <= 0:
            return True, "ack_replicas=0"
        t0 = time.perf_counter()
        with self._lock:
            followers = list(self._followers.values())
        jobs = []
        for f in followers:
            prev = f.inflight
            if prev is not None:
                if not prev.done():
                    continue   # still shipping a previous tick: miss
                f.inflight = None
            if not f.breaker.allow():
                continue       # open breaker: skip, don't pay timeout
            fut = self._pool.submit(self._ship, f)
            f.inflight = fut
            jobs.append(fut)
        acked = 0
        pending = set(jobs)
        deadline = t0 + self.timeout + 0.05
        while pending and acked < need:
            budget = deadline - time.perf_counter()
            if budget <= 0:
                break
            done, pending = futures_wait(
                pending, timeout=budget,
                return_when=FIRST_COMPLETED)
            for fut in done:
                if fut.result():
                    acked += 1
        barrier_s = time.perf_counter() - t0
        self._m_barrier.observe(barrier_s, group=self.group)
        self._m_barrier_sketch.observe(barrier_s, group=self.group)
        if acked >= need:
            return True, f"{acked}/{need} follower acks"
        return False, (f"write concern unmet: {acked}/{need} "
                       f"follower acks ({len(followers)} followers)")

    def _ship(self, f: _Follower) -> bool:
        """Ship `pack_since(f.mark)` to one follower via the
        ``replicate`` op and record its durable head. Runs on the
        replicator pool; the tier lock bounds the pack read only."""
        from .ops.packing import pack_rows
        tier = self.tier
        try:
            sock = f.conn.ensure(self.tally)
            sem_ok = "semantics" in f.conn.caps
            with tier.lock:
                head = tier.crdt.canonical_time
                packed, ids = _pack_for_peer(tier.crdt, f.mark,
                                             sem_ok)
            if packed.k:
                meta, bufs = pack_rows(packed)
                send_frame(sock, {"op": "replicate", "meta": meta,
                                  "node_ids": list(ids)},
                           self.tally, f.conn.codec)
                send_bytes_frame(sock, bufs, self.tally, f.conn.codec)
                reply = recv_frame(
                    sock, deadline=time.monotonic() + self.timeout,
                    tally=self.tally, codec=f.conn.codec)
                if not isinstance(reply, dict) or not reply.get("ok"):
                    raise ConnectionError(
                        f"replicate rejected: {reply!r}")
                f.durable = reply.get("hlc")
            f.mark = head
            f.breaker.record_success()
            self._m_acks.inc(group=self.group, follower=f.name,
                             outcome="ok")
            return True
        except (SyncError, ConnectionError, OSError, ValueError,
                socket.timeout) as e:
            f.conn.reset()
            f.breaker.record_failure()
            self._m_acks.inc(group=self.group, follower=f.name,
                             outcome=type(e).__name__)
            return False


class _HbClient:
    """One persistent blocking heartbeat session to a member — the
    pre-hello untagged framing, since liveness probing must not
    depend on capability negotiation."""

    def __init__(self, addr: str, timeout: float):
        self.addr = addr
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None

    def beat(self, lease: Optional[dict] = None,
             want_root: bool = False) -> dict:
        msg: dict = {"op": "heartbeat"}
        if lease is not None:
            msg["lease"] = lease
        if want_root:
            msg["want_root"] = True
        try:
            if self._sock is None:
                host, port = _split_addr(self.addr)
                self._sock = socket.create_connection(
                    (host, port), timeout=self._timeout)
                self._sock.settimeout(self._timeout)
            send_frame(self._sock, msg)
            reply = recv_frame(
                self._sock,
                deadline=time.monotonic() + self._timeout)
        except (ConnectionError, OSError, ValueError,
                socket.timeout) as e:
            self.close()
            raise ConnectionError(f"heartbeat {self.addr}: {e!r}") \
                from e
        if not isinstance(reply, dict) or not reply.get("ok"):
            self.close()
            raise ConnectionError(
                f"heartbeat {self.addr}: bad reply {reply!r}")
        return reply

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class _Member:
    __slots__ = ("index", "name", "tier", "addr", "role", "misses",
                 "last", "generation", "hb")

    def __init__(self, index: int, name: str):
        self.index = index
        self.name = name
        self.tier: Optional[ServeTier] = None
        self.addr: Optional[str] = None
        self.role = "follower"      # follower | primary | down
        self.misses = 0
        self.last: dict = {}        # newest heartbeat reply
        self.generation = 0         # bumps on every rejoin
        self.hb: Optional[_HbClient] = None


class ReplicaGroup:
    """N replicas behind one keyspace arc: spawn, monitor, fail over,
    rejoin. Standalone (its own single-owner routing table) or as one
    partition of a `FederatedTier` (which passes ``table``/
    ``on_promote`` and publishes flips fleet-wide).

    ``make_crdt(replica_index, generation)`` builds each member's
    store; generation bumps on every rejoin so a restarted member
    NEVER reuses its crash image. ``addr_via`` maps a member's real
    listen address to the address the group advertises (routing
    table, replicator targets, heartbeats) — the test seam that puts
    a `FaultProxy` in front of every wire the group uses.
    """

    # Checked by analysis/concurrency.py: the group lock (monitor,
    # promotion, membership) may be held while a member tier's store
    # lock is taken; the reverse never happens — _on_promote re-enters
    # FederatedTier._control only AFTER this lock is released (the
    # PR 15 invariant).
    _CRDTLINT_LOCK_ORDER = ("_lock", ("tier.lock", "ServeTier.lock"))

    def __init__(self, n_slots: int, replicas: int = 3,
                 ack_replicas: int = 1, host: str = "127.0.0.1",
                 group: str = "g0",
                 make_crdt: Optional[Callable] = None,
                 flush_interval: float = 0.002,
                 heartbeat_interval: float = 0.05,
                 heartbeat_timeout: float = 0.25,
                 lease_misses: int = 4,
                 lease_ttl: Optional[float] = None,
                 replicate_timeout: float = 0.25,
                 table: Optional[RoutingTable] = None,
                 on_promote: Optional[Callable] = None,
                 addr_via: Optional[Callable[[str], str]] = None,
                 tier_kwargs: Optional[dict] = None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1; got {replicas}")
        if ack_replicas > replicas - 1:
            raise ValueError(
                f"ack_replicas={ack_replicas} needs more followers "
                f"than {replicas} replicas provide")
        self.n_slots = int(n_slots)
        self.replicas = int(replicas)
        self.ack_replicas = int(ack_replicas)
        self.host = host
        self.group = str(group)
        self.flush_interval = flush_interval
        self.heartbeat_interval = float(heartbeat_interval)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.lease_misses = int(lease_misses)
        # The fence window a partitioned ex-primary serves out before
        # it stops acking: generous enough that heartbeat jitter
        # cannot fence a healthy primary, short enough that the
        # dual-primary overlap after a promotion stays bounded (and
        # harmless — both sides' writes are joinable; see module doc).
        self.lease_ttl = (float(lease_ttl) if lease_ttl is not None
                          else heartbeat_interval * lease_misses * 2)
        self.replicate_timeout = float(replicate_timeout)
        self._make_crdt = (make_crdt if make_crdt is not None
                           else self._default_crdt)
        self.on_promote = on_promote
        self._addr_via = addr_via if addr_via is not None \
            else (lambda a: a)
        self._tier_kwargs = dict(tier_kwargs or {})
        self.table = table
        self.members: List[_Member] = [
            _Member(i, f"{self.group}-r{i}")
            for i in range(self.replicas)]
        self._lock = make_lock("ReplicaGroup._lock", 30, rlock=True)
        self._lease_epoch = 1
        self._primary: Optional[_Member] = None
        # The table owner a pending flip must replace — survives a
        # no-candidate election round so a LATER promotion still
        # reassigns the dead primary's arcs.
        self._flip_addr: Optional[str] = None
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._hb_pool: Optional[ThreadPoolExecutor] = None
        self.failovers = 0
        self.last_failover_s: Optional[float] = None

        from .obs.registry import default_registry
        reg = default_registry()
        self._m_failover = reg.counter(
            "crdt_tpu_failover_total",
            "primary failovers driven by the group monitor")
        self._m_health = reg.gauge(
            "crdt_tpu_replica_health",
            "per-replica liveness as seen by the group monitor "
            "(1 = beating, 0 = declared down)")

    def _default_crdt(self, index: int, generation: int):
        from .models.dense_crdt import DenseCrdt
        return DenseCrdt(f"{self.group}-r{index}.{generation}",
                         self.n_slots)

    # --- lifecycle ---

    def start(self) -> "ReplicaGroup":
        with self._lock:
            for m in self.members:
                self._spawn(m)
            primary = self.members[0]
            primary.role = "primary"
            primary.tier.role = "primary"
            self._primary = primary
            if self.table is None and self.on_promote is None:
                # Standalone groups own their table. Under a
                # federation (`on_promote` set) the FLEET table is the
                # authority — pre-installing a private epoch-0 table
                # here would tie with the fleet's epoch-0 publish and
                # `PartitionRouter.install` keeps the incumbent on
                # ties, wedging every member on the private view.
                self.table = RoutingTable.even(
                    self.n_slots, [primary.addr])
            if self.table is not None:
                self.install_table(self.table)
            self._attach_replicator(primary)
        self._hb_pool = ThreadPoolExecutor(
            max_workers=self.replicas,
            thread_name_prefix=f"{self.group}-hb")
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"{self.group}-monitor")
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        monitor, self._monitor = self._monitor, None
        if monitor is not None:
            monitor.join(timeout=30)
        if self._hb_pool is not None:
            self._hb_pool.shutdown(wait=False)
            self._hb_pool = None
        with self._lock:
            members = list(self.members)
        for m in members:
            if m.hb is not None:
                m.hb.close()
            tier = m.tier
            if tier is not None:
                rep = tier.replicator
                if rep is not None:
                    rep.close()
                try:
                    tier.stop()
                except RuntimeError:
                    pass

    def __enter__(self) -> "ReplicaGroup":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- spawn / membership ---

    def _spawn(self, m: _Member) -> None:
        crdt = self._make_crdt(m.index, m.generation)
        router = PartitionRouter()
        tier = ServeTier(crdt, host=self.host, port=0,
                         flush_interval=self.flush_interval,
                         router=router, **self._tier_kwargs)
        tier.group_name = self.group
        tier.role = "follower"
        tier.start()
        m.tier = tier
        m.addr = self._addr_via(f"{tier.host}:{tier.port}")
        # The router believes the ADVERTISED address: `owns` must
        # agree with the table the group publishes, proxy or not.
        router.bind(m.addr)
        m.hb = _HbClient(m.addr, self.heartbeat_timeout)
        m.misses = 0
        m.role = "follower"
        self._m_health.set(1, group=self.group, replica=m.name)

    def _attach_replicator(self, primary: _Member) -> None:
        followers = {m.name: m.addr for m in self.members
                     if m is not primary and m.role == "follower"}
        primary.tier.replicator = Replicator(
            primary.tier, followers, ack_replicas=self.ack_replicas,
            timeout=self.replicate_timeout, group=self.group)

    def install_table(self, table: RoutingTable) -> None:
        """Install ``table`` on every live member's router. MUST stay
        lock-free: federation calls this under its control lock while
        the promote path runs group-lock → control-lock — taking the
        group lock here would complete the deadlock cycle. Router
        `install` is itself epoch-guarded and thread-safe."""
        self.table = table
        for m in self.members:
            tier = m.tier
            if tier is not None and tier.router is not None \
                    and not tier.killed:
                tier.router.install(table)

    # --- queries ---

    @property
    def primary(self) -> Optional[_Member]:
        with self._lock:
            return self._primary

    def primary_addr(self) -> Optional[str]:
        m = self.primary
        return None if m is None else m.addr

    def member_addrs(self) -> List[str]:
        with self._lock:
            return [m.addr for m in self.members
                    if m.addr is not None and m.role != "down"]

    # --- fault injection (tests / bench) ---

    def kill(self, index: int) -> _Member:
        """Abruptly kill one member (RST, no drain). Group state is
        NOT updated here — the monitor must discover the death over
        the wire, which is exactly the MTTR the bench measures."""
        m = self.members[index]
        m.tier.kill()
        return m

    def kill_primary(self) -> _Member:
        m = self.primary
        if m is None:
            raise RuntimeError("no live primary to kill")
        return self.kill(m.index)

    # --- monitor / failover ---

    def _monitor_loop(self) -> None:
        interval = self.heartbeat_interval
        while not self._stop.wait(interval):
            with self._lock:
                live = [m for m in self.members if m.role != "down"]
                primary = self._primary
                lease = None
                if primary is not None:
                    lease = {"holder": f"{self.group}-monitor",
                             "ttl_ms": self.lease_ttl * 1000.0,
                             "epoch": self._lease_epoch}
            futs = {m: self._hb_pool.submit(
                        m.hb.beat,
                        lease if m is primary else None)
                    for m in live}
            for m, fut in futs.items():
                try:
                    m.last = fut.result()
                    m.misses = 0
                    self._m_health.set(1, group=self.group,
                                       replica=m.name)
                except Exception:
                    m.misses += 1
            dead_primary = None
            with self._lock:
                for m in live:
                    if m.misses >= self.lease_misses:
                        self._m_health.set(0, group=self.group,
                                           replica=m.name)
                        if m is self._primary:
                            dead_primary = m
                        else:
                            self._drop_follower(m)
            if dead_primary is not None or self.primary is None:
                self._failover(dead_primary)

    def _drop_follower(self, m: _Member) -> None:
        """A follower that stopped beating leaves the write-concern
        set so barriers stop paying its timeout; `rejoin` re-adds
        it. Caller holds the group lock."""
        m.role = "down"
        primary = self._primary
        if primary is not None and primary.tier is not None:
            rep = primary.tier.replicator
            if rep is not None:
                rep.drop_follower(m.name)

    def _failover(self, dead: Optional[_Member]) -> None:
        from .obs.trace import span
        t0 = time.perf_counter()
        with self._lock:
            if dead is not None:
                dead.role = "down"
                if self._primary is dead:
                    self._primary = None
                    self._flip_addr = dead.addr
                self._m_health.set(0, group=self.group,
                                   replica=dead.name)
            if self._primary is not None:
                return
            candidates = [m for m in self.members
                          if m.role == "follower"]
            old_addr = self._flip_addr
        if not candidates:
            return     # nothing electable yet; retried next round
        with span("failover", kind="failover", group=self.group,
                  dead=(dead.name if dead is not None else None)):
            # Election: freshest durable head wins; digest root, then
            # name, break ties deterministically. A candidate that
            # cannot answer the probe is not electable.
            scored = []
            for m in candidates:
                try:
                    reply = m.hb.beat(want_root=True)
                except ConnectionError:
                    continue
                try:
                    head = Hlc.parse(str(reply.get("hlc")))
                except (ValueError, TypeError):
                    continue
                scored.append(
                    (head, int(reply.get("root", 0) or 0), m.name, m))
            if not scored:
                return
            scored.sort(key=lambda s: (s[0], s[1], s[2]))
            winner = scored[-1][3]
            # Close the ack-coverage gap BEFORE the routing flip:
            # with ack_replicas < followers, each tick's write
            # concern is satisfied by whichever follower acked
            # first, so no single follower — the freshest-head
            # winner included — is guaranteed a superset of every
            # acked row. Lattice-join the winner from each
            # reachable survivor so promotion never buries a row
            # some other follower acked. Best-effort per survivor:
            # losing the primary AND the only follower holding a
            # tick exceeds what ack_replicas=1 promises.
            for m in candidates:
                if m is winner or m.addr is None:
                    continue
                host, port = _split_addr(m.addr)
                for attempt in range(2):
                    try:
                        conn = PeerConnection(
                            host, port,
                            timeout=self.heartbeat_timeout * 4)
                    except (ConnectionError, OSError):
                        continue
                    try:
                        sync_merkle_over_conn(
                            winner.tier.crdt, conn,
                            lock=winner.tier.lock)
                        break
                    except SyncProtocolError:
                        break
                    except (ConnectionError, OSError):
                        pass
                    finally:
                        conn.close()
            self._promote(winner, old_addr)
        elapsed = time.perf_counter() - t0
        with self._lock:
            self.failovers += 1
            self.last_failover_s = elapsed
        self._m_failover.inc(group=self.group)

    def _promote(self, winner: _Member, old_addr: Optional[str]
                 ) -> None:
        """Routing flip + role flip. The dead primary is never
        touched (it may genuinely be gone, or partitioned — its lease
        fence handles the latter); the winner gets a fresh
        `Replicator` over the remaining live followers and the table
        epoch bumps so every stale client is refused into a refresh."""
        with self._lock:
            winner.role = "primary"
            self._primary = winner
            self._flip_addr = None
            self._lease_epoch += 1
            self._attach_replicator(winner)
            winner.tier.role = "primary"
            table = self.table
            if table is not None and old_addr is not None \
                    and old_addr in table.owners():
                table = table.reassign(old_addr, winner.addr)
        if self.on_promote is not None:
            # Called with the group lock RELEASED (``_primary`` is
            # already visible): federation takes its control lock in
            # here, and a concurrent split holding that control lock
            # polls `primary` (group lock) — invoking the callback
            # under the group lock would complete a deadlock cycle.
            self.on_promote(self, table)
        elif table is not None:
            self.install_table(table)
        # Seed the new primary's lease immediately — the next monitor
        # round would too, but the write path is fenced-free sooner.
        try:
            winner.hb.beat(lease={
                "holder": f"{self.group}-monitor",
                "ttl_ms": self.lease_ttl * 1000.0,
                "epoch": self._lease_epoch})
        except ConnectionError:
            pass

    # --- rejoin ---

    @staticmethod
    def _count_rejoin_bytes(crdt) -> None:
        """Live/tombstone byte split of what a rejoin walk pulled
        into the fresh store (docs/STORAGE.md): the primary ran GC
        first, so tombstone_bytes ≈ 0 is the measurable payoff —
        every tombstone here is one GC could not yet prove stable.
        Wire row width matches the packed lane layout (slot 4 + lt 8
        + node 4 + val 8 + tomb 1)."""
        store = getattr(crdt, "store", None)
        if store is None or not hasattr(store, "tomb"):
            return
        import numpy as np
        from .obs.registry import default_registry
        occ = np.asarray(store.occupied)
        tomb_rows = int((occ & np.asarray(store.tomb)).sum())
        live_rows = int(occ.sum()) - tomb_rows
        reg = default_registry()
        reg.counter(
            "crdt_tpu_shipped_live_bytes_total",
            "packed bytes of live rows shipped by migration streams "
            "and rejoin walks (surface label: migrate|rejoin)").inc(
                live_rows * 25, surface="rejoin")
        reg.counter(
            "crdt_tpu_shipped_tombstone_bytes_total",
            "packed bytes of tombstone rows shipped by migration "
            "streams and rejoin walks (surface label: "
            "migrate|rejoin)").inc(tomb_rows * 25, surface="rejoin")

    def rejoin(self, index: int) -> _Member:
        """Restart a down member: FRESH store, merkle catch-up from
        the current primary, then re-enter as a follower in the
        write-concern set. The crash image is discarded — recovery is
        resync, not replay (ROADMAP item 5 is the replay path)."""
        m = self.members[index]
        with self._lock:
            primary = self._primary
            if m.role != "down" and m.tier is not None \
                    and not m.tier.killed:
                raise RuntimeError(f"{m.name} is still live")
            if primary is None:
                raise RuntimeError("no live primary to rejoin from")
            m.generation += 1
            prev_port = 0 if m.tier is None else (m.tier.port or 0)
        crdt = self._make_crdt(m.index, m.generation)
        # Spend the GC bytes (docs/STORAGE.md): one epoch-GC pass on
        # the primary BEFORE the catch-up walk, so the rejoining
        # member pulls live rows only — stable tombstones are purged
        # instead of shipped. With this member down the durable set
        # is usually short a mark, which PINS the watermark and
        # purges nothing: unmeasured is never safe-to-purge, and the
        # walk simply ships the tombstones too.
        if primary.tier is not None \
                and hasattr(primary.tier, "gc_pass"):
            primary.tier.gc_pass()
        # Catch up BEFORE serving: the walk pulls everything the
        # group committed while this member was dead (and pushes
        # nothing — the store is fresh).
        # The walk only PULLS into the fresh store, so re-running it
        # after a transport fault is idempotent — and each pass has
        # less left to fetch. A proxied/chaos wire dropping one
        # connection must not fail the whole rejoin; a protocol
        # rejection (explicit error report) stays fatal.
        host, port = _split_addr(primary.addr)
        last: Optional[Exception] = None
        for attempt in range(6):
            try:
                conn = PeerConnection(
                    host, port, timeout=self.heartbeat_timeout * 4)
            except (ConnectionError, OSError) as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
                continue
            try:
                sync_merkle_over_conn(crdt, conn)
                last = None
                break
            except SyncProtocolError:
                raise
            except (ConnectionError, OSError) as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
            finally:
                conn.close()
        if last is not None:
            raise ConnectionError(
                f"rejoin catch-up from {primary.addr} failed after "
                f"retries: {last!r}")
        self._count_rejoin_bytes(crdt)
        with self._lock:
            router = PartitionRouter()
            # Rebind the member's previous listen address: a crashed
            # process restarts at the same host:port, so clients
            # seeded with the original fleet addresses can always
            # rediscover the group no matter how many failovers have
            # happened. Ephemeral fallback if the bind races.
            try:
                tier = ServeTier(crdt, host=self.host,
                                 port=prev_port,
                                 flush_interval=self.flush_interval,
                                 router=router, **self._tier_kwargs)
                tier.group_name = self.group
                tier.role = "follower"
                tier.start()
            except OSError:
                tier = ServeTier(crdt, host=self.host, port=0,
                                 flush_interval=self.flush_interval,
                                 router=router, **self._tier_kwargs)
                tier.group_name = self.group
                tier.role = "follower"
                tier.start()
            m.tier = tier
            m.addr = self._addr_via(f"{tier.host}:{tier.port}")
            router.bind(m.addr)
            if self.table is not None:
                router.install(self.table)
            if m.hb is not None:
                m.hb.close()
            m.hb = _HbClient(m.addr, self.heartbeat_timeout)
            m.misses = 0
            m.role = "follower"
            primary = self._primary
            if primary is not None and primary.tier is not None:
                rep = primary.tier.replicator
                if rep is not None:
                    # mark=None on the fresh follower: the first
                    # barrier ships one full pack — wasteful after a
                    # merkle walk, but immune to stamps that raced
                    # the walk; the second barrier is incremental.
                    rep.add_follower(m.name, m.addr)
            self._m_health.set(1, group=self.group, replica=m.name)
        return m

"""crdtlint CLI: ``python -m crdt_tpu.analysis``.

Default run (no explicit targets) audits the shipped tree — the CI
gate: host-lint every package file, run the semilattice law search
over the registered kernels, and audit every merge jaxpr for
order-sensitivity hazards. Exit 0 means no findings.

Explicit targets (``--lint PATH``, ``--law-fixture PATH``) run ONLY
what was named — how the self-test fixtures are exercised::

    python -m crdt_tpu.analysis --lint tests/fixtures/racy_gossip.py
    python -m crdt_tpu.analysis --law-fixture tests/fixtures/broken_merge.py

A law fixture is a Python file exposing ``LAW_TARGETS`` (a list of
``analysis.lattice_laws.LawTarget``); on a law violation the CLI
prints the violating input (seed + batches) and exits nonzero.

``--json`` emits machine-readable output; its ``jaxpr_reports`` key
carries each audited kernel's golden report (hazards + relied-on
contracts), which tests pin for the Pallas fan-in path.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
from typing import List


def _load_law_fixture(path: str):
    spec = importlib.util.spec_from_file_location(
        "crdtlint_law_fixture_" + os.path.basename(path).replace(
            ".", "_"), path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    targets = getattr(module, "LAW_TARGETS", None)
    if not targets:
        raise SystemExit(
            f"law fixture {path} defines no LAW_TARGETS list")
    return list(targets)


def _registry_completeness() -> List:
    """The semantics-registry CI gate (docs/TYPES.md): every
    registered lane semantics must declare BOTH a law target and an
    audit target — registering is what puts a type under CI, so a spec
    missing either would ship an unverified kernel. One finding per
    missing factory; the default run fails on them like any other."""
    from .findings import Finding
    from ..semantics import all_semantics
    out = []
    for spec in all_semantics():
        where = f"semantics {spec.name!r} (tag {spec.tag})"
        if spec.law_target is None:
            out.append(Finding(
                rule="semantics-missing-law-target",
                path="crdt_tpu/semantics/types.py", line=0,
                message=f"{where} registers no law target",
                detail="declare law_target so the seeded semilattice "
                       "search covers this kernel (see "
                       "types._typed_law_target)"))
        if spec.audit_target is None:
            out.append(Finding(
                rule="semantics-missing-audit-target",
                path="crdt_tpu/semantics/types.py", line=0,
                message=f"{where} registers no audit target",
                detail="declare audit_target so the jaxpr audit "
                       "covers this kernel (see "
                       "types._typed_audit_target)"))
    return out


# Fast-path kernels the default run must find in the jaxpr-audit
# registry: shipping either without audit coverage would let an
# order-sensitivity hazard into the hottest dispatch (docs/FASTPATH.md).
_FASTPATH_REQUIRED = (
    "dense.merge_repack_step",
    "pallas.ingest_scatter_tiles[interpret]",
    "parallel.collective_join[member2]",
)


def _fastpath_completeness(target_names) -> List:
    """The fast-path CI gate: the fused merge+repack program, the
    touched-tile ingest scatter and the pod-local collective join must
    be registered audit targets — an unregistered fast-path kernel
    fails the default run. The collective target needs a 2-device
    member mesh, so on a single-device host it is exempt rather than
    spuriously missing."""
    from .findings import Finding
    names = set(target_names)
    out = []
    for req in _FASTPATH_REQUIRED:
        if req.startswith("parallel.collective_join"):
            import jax
            if len(jax.devices()) < 2:
                continue
        if req not in names:
            out.append(Finding(
                rule="fastpath-kernel-unregistered",
                path="crdt_tpu/analysis/jaxpr_audit.py", line=0,
                message=f"fast-path kernel {req!r} is not a "
                        "registered jaxpr-audit target",
                detail="add it to builtin_targets() so the audit "
                       "covers the fused/zero-copy dispatch path "
                       "(docs/FASTPATH.md)"))
    return out


# Merkle anti-entropy kernels the default run must find in the
# jaxpr-audit registry: the digest reduction and the range-pack mask
# drive the cold-peer sync path (docs/ANTIENTROPY.md).
_MERKLE_REQUIRED = (
    "digest.digest_tree_levels",
    "dense.range_delta_mask",
)


def _merkle_completeness(target_names) -> List:
    """The merkle CI gate: the on-device digest-tree reduction and the
    slot-range delta mask must be registered audit targets — an
    unregistered anti-entropy kernel fails the default run."""
    from .findings import Finding
    names = set(target_names)
    out = []
    for req in _MERKLE_REQUIRED:
        if req not in names:
            out.append(Finding(
                rule="merkle-kernel-unregistered",
                path="crdt_tpu/analysis/jaxpr_audit.py", line=0,
                message=f"merkle anti-entropy kernel {req!r} is not a "
                        "registered jaxpr-audit target",
                detail="add it to builtin_targets() so the audit "
                       "covers the digest-reduction/range-pack "
                       "dispatch path (docs/ANTIENTROPY.md)"))
    return out


# Device entry points the default run must find registered with the
# dispatch ledger (obs.device): every jit/shard_map program a model or
# transport path can dispatch. An uninstrumented kernel is invisible to
# the compile census and breaks the zero-dispatch invariant probes
# (docs/OBSERVABILITY.md, device plane).
_LEDGER_REQUIRED = (
    # ops/dense.py — XLA executors, scatters, pack masks
    "dense.fanin_step", "dense.fanin_stream", "dense.sparse_fanin_step",
    "dense.wire_join_step", "dense.merge_repack_step",
    "dense.delta_mask", "dense.range_delta_mask",
    "dense.max_logical_time", "dense.put_scatter",
    "dense.record_scatter", "dense.delete_scatter",
    "dense.ingest_scatter",
    # ops/digest.py — the merkle reduction
    "digest.digest_tree_device",
    # ops/pallas_scatter.py + ops/pallas_merge.py — Mosaic routes
    "pallas.ingest_scatter_tiles",
    "pallas.model_fanin_batch", "pallas.model_fanin_split",
    "pallas.pipelined_model_step", "pallas.pipelined_model_step_split",
    # semantics/kernels.py — the typed fan-in family
    "semantics.typed_wire_join_step", "semantics.typed_sparse_join_step",
    "semantics.typed_fanin_step",
    # parallel/fanin.py — shard_map programs
    "parallel.sharded_fanin", "parallel.sharded_pallas_fanin",
    "parallel.sharded_ingest", "parallel.sharded_digest",
    "parallel.sharded_delta_mask", "parallel.sharded_max_logical_time",
    # parallel/collective.py — the pod-local group join
    "parallel.collective_join",
    # storage plane (docs/STORAGE.md) — epoch GC + online compaction
    "dense.gc_purge", "dense.compact_remap", "parallel.sharded_compact",
)


def _ledger_completeness(registered=None) -> List:
    """The dispatch-ledger CI gate: every device entry point must have
    declared itself to the ledger at module import — an uninstrumented
    kernel dispatches invisibly and fails the default run."""
    from .findings import Finding
    if registered is None:
        # Importing the instrumented modules runs their register()
        # calls; nothing is dispatched.
        from .. import parallel  # noqa: F401
        from ..obs.device import default_ledger
        from ..ops import (dense, digest, pallas_merge,  # noqa: F401
                           pallas_scatter)
        from ..semantics import kernels  # noqa: F401
        registered = default_ledger().registered_kernels()
    names = set(registered)
    out = []
    for req in _LEDGER_REQUIRED:
        if req not in names:
            out.append(Finding(
                rule="dispatch-ledger-unregistered",
                path="crdt_tpu/obs/device.py", line=0,
                message=f"device entry point {req!r} is not "
                        "registered with the dispatch ledger",
                detail="instrument its host wrapper with "
                       "obs.device.record(...) and register the name "
                       "at module import so dispatch counts, the "
                       "compile census and the zero-dispatch probes "
                       "cover it (docs/OBSERVABILITY.md)"))
    return out


# Storage-plane kernels (docs/STORAGE.md) the default run must find
# covered by BOTH verification surfaces: the jaxpr audit (an epoch-GC
# purge that reorders under donation corrupts silently) and the law
# search (purge composed with the merge-side resurrection fence must
# stay a semilattice — idempotent, commutative, associative — or
# replica states diverge permanently).
_GC_REQUIRED = (
    "dense.gc_purge",
    "dense.compact_remap",
)


def _gc_completeness(audit_names=None, law_names=None) -> List:
    """The storage-plane CI gate: epoch GC and online compaction must
    be registered with every verification surface that ran this
    invocation (pass ``None`` for one that did not run). A physically
    destructive kernel shipping without audit or law coverage is the
    one class of bug eventual consistency cannot repair."""
    from .findings import Finding
    out = []
    for req in _GC_REQUIRED:
        if audit_names is not None and req not in set(audit_names):
            out.append(Finding(
                rule="gc-kernel-unaudited",
                path="crdt_tpu/analysis/jaxpr_audit.py", line=0,
                message=f"storage-plane kernel {req!r} is not a "
                        "registered jaxpr-audit target",
                detail="add it to builtin_targets() — a donated "
                       "purge/remap with an order-sensitivity hazard "
                       "destroys state unrecoverably "
                       "(docs/STORAGE.md)"))
        if law_names is not None and req not in set(law_names):
            out.append(Finding(
                rule="gc-kernel-unlawed",
                path="crdt_tpu/analysis/lattice_laws.py", line=0,
                message=f"storage-plane kernel {req!r} is not a "
                        "registered law-search target",
                detail="add it to builtin_targets() — purge + fence "
                       "must provably stay a semilattice or replicas "
                       "diverge permanently (docs/STORAGE.md)"))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m crdt_tpu.analysis",
        description="crdtlint: jaxpr lattice auditor + host-layer "
                    "race/discipline linter")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--lint", action="append", default=[],
                        metavar="PATH",
                        help="lint only this file/directory (repeat "
                             "to add more); disables the default "
                             "full-tree run")
    parser.add_argument("--law-fixture", action="append", default=[],
                        metavar="PATH",
                        help="run the law search over a fixture "
                             "module's LAW_TARGETS instead of the "
                             "builtin kernels")
    parser.add_argument("--seeds", default="0,1,2",
                        help="comma-separated seeds for the law "
                             "counterexample search (default 0,1,2)")
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the host linter in the default run")
    parser.add_argument("--skip-laws", action="store_true",
                        help="skip the law search in the default run")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="skip the jaxpr audit in the default run")
    args = parser.parse_args(argv)

    from .findings import Finding, render_human, render_json
    findings: List[Finding] = []
    reports = []
    seeds = tuple(int(s) for s in args.seeds.split(",") if s.strip())
    explicit = bool(args.lint or args.law_fixture)

    if args.lint:
        from .concurrency import analyze_paths
        from .host_lint import lint_file, lint_package
        for path in args.lint:
            if os.path.isdir(path):
                findings.extend(lint_package(path))
            else:
                findings.extend(lint_file(path))
        # one global lock graph across every --lint path
        findings.extend(analyze_paths(args.lint))

    if args.law_fixture:
        from .lattice_laws import run_laws
        for path in args.law_fixture:
            findings.extend(run_laws(_load_law_fixture(path),
                                     seeds=seeds))

    if not explicit:
        if not args.skip_lint:
            from .concurrency import analyze_package
            from .host_lint import lint_package
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            findings.extend(lint_package(pkg_root))
            findings.extend(analyze_package(pkg_root))
        if not args.skip_laws or not args.skip_jaxpr:
            # The registry gate guards exactly the law + jaxpr
            # coverage surfaces, so it runs whenever either does.
            findings.extend(_registry_completeness())
        law_names = audit_names = None
        if not args.skip_laws:
            from .lattice_laws import builtin_targets, run_laws
            law_targets = builtin_targets()
            law_names = tuple(t.name for t in law_targets)
            findings.extend(run_laws(law_targets, seeds=seeds))
        if not args.skip_jaxpr:
            from .jaxpr_audit import audit_all, builtin_targets as \
                audit_targets
            targets = audit_targets()
            names = tuple(t.name for t in targets)
            audit_names = names
            findings.extend(_fastpath_completeness(names))
            findings.extend(_merkle_completeness(names))
            findings.extend(_ledger_completeness())
            reports, audit_findings = audit_all(targets)
            findings.extend(audit_findings)
        if not args.skip_laws or not args.skip_jaxpr:
            findings.extend(_gc_completeness(audit_names, law_names))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json:
        print(render_json(
            findings,
            jaxpr_reports=[r.golden() for r in reports]))
    else:
        audited = (f" ({len(reports)} kernels audited)"
                   if reports else "")
        if findings:
            print(render_human(findings))
        else:
            print(f"crdtlint: clean{audited}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

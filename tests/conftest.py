"""Test configuration: force an 8-virtual-device CPU JAX platform.

Tests must run without TPU hardware; multi-chip sharding is validated on
a virtual CPU mesh (the driver separately dry-runs the multichip path).
The env vars must be set before jax initializes its backends.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may pin JAX_PLATFORMS to a hardware plugin before this
# file runs (site customization), so the env-var route is not enough —
# override the config directly, before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _isolate_metrics_registry():
    """Fence each test module's metrics off from every other module.

    The default registry is a process-global: serve tiers observe ack
    latencies into it, gossip nodes count rounds, the fleet poller's
    SLO verdict reads whatever has accumulated. Without isolation,
    outcomes depend on module collection order (test_serve_federation
    once had to *sort after* test_obs.py). Snapshot on module entry,
    restore on exit — samples recorded inside the module vanish,
    instruments and cached references stay valid."""
    from crdt_tpu.obs.registry import default_registry

    reg = default_registry()
    snap = reg.state_snapshot()
    yield
    reg.restore_state(snap)

"""Zero-copy fast path (docs/FASTPATH.md): arena-packed deltas whose
buffers reach ``sendmsg`` unchanged, the fused merge+repack relay
dispatch, the bounded pack cache, and the single-dispatch ingest
commits (touched-tile Mosaic kernel, sharded shard_map program).

The acceptance checks the ISSUE pins live here: buffer identity across
pack → frame (no hidden copy re-materializes a lane), bit-identical
``PackedDelta`` round-trips, and fused-relay equivalence with the
two-dispatch path it replaced."""

import socket
import struct
import threading

import numpy as np
import pytest

import jax

from crdt_tpu import DenseCrdt, FrameCodec
from crdt_tpu.models.dense_crdt import ShardedDenseCrdt
from crdt_tpu.net import recv_bytes_frame, send_bytes_frame
from crdt_tpu.obs.registry import default_registry
from crdt_tpu.ops.packing import (PackedDelta, arena_of, pack_rows,
                                  unpack_rows)
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.testing import FakeClock

pytestmark = pytest.mark.fastpath

BASE = 1_700_000_000_000
N = 64


def _copy_counter():
    return default_registry().counter(
        "crdt_tpu_pack_copy_bytes_total",
        "bytes copied between pack and frame (zero on the "
        "arena fast path)")


def _make(node="n", n_slots=N, **kw):
    return DenseCrdt(node, n_slots=n_slots,
                     wall_clock=FakeClock(start=BASE), **kw)


# ------------------------------------------------ zero-copy pack path


def test_pack_since_lanes_share_one_arena_and_frame_zero_copy():
    """The acceptance check: every lane of one packed delta roots at
    ONE arena allocation, `pack_rows` frames that same storage (the
    memoryviews' owners walk back to the identical buffer), and the
    pack-path copy counter does not move — the gather wrote the bytes
    `sendmsg` would ship."""
    c = _make()
    c.put_batch(list(range(16)), [v * 10 for v in range(16)])
    c.delete_batch([3, 7])
    before = _copy_counter().value(stage="pack_rows")
    packed, _ = c.pack_since(None)
    arena = arena_of(packed.slots)
    for lane in packed:
        if lane is not None:
            assert arena_of(lane) is arena
    meta, bufs = pack_rows(packed)
    for mv in bufs:
        assert isinstance(mv, memoryview)
        assert arena_of(mv.obj) is arena
    assert _copy_counter().value(stage="pack_rows") == before
    # The buffer id is stable across repeated framing of the same
    # delta — no per-send re-materialization.
    _, bufs2 = pack_rows(packed)
    assert [m.obj is m2.obj for m, m2 in zip(bufs, bufs2)] \
        == [True] * len(bufs)


def test_foreign_lane_copy_is_counted():
    """Hand-built deltas with wrong dtypes take the one legitimate
    normalization copy — and the counter records exactly it."""
    d = PackedDelta(slots=np.array([1, 2], np.int64),   # wrong: int64
                    lt=np.array([5, 6], np.int64),
                    node=np.array([0, 0], np.int32),
                    val=np.array([10, 20], np.int64),
                    tomb=np.array([0, 0], np.uint8))
    before = _copy_counter().value(stage="pack_rows")
    pack_rows(d)
    # only the slots lane (2 × int32 after normalization) was copied
    assert _copy_counter().value(stage="pack_rows") == before + 8


def test_packed_roundtrip_bit_identical():
    c = _make()
    c.put_batch(list(range(0, 40, 3)), list(range(100, 140, 3)))
    c.delete_batch([6, 12])
    packed, ids = c.pack_since(None)
    meta, bufs = pack_rows(packed)
    back = unpack_rows(meta, b"".join(bytes(b) for b in bufs))
    for a, b in zip(packed, back):
        if a is None:
            assert b is None
        else:
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)
    # And the round-tripped delta merges to the identical store.
    r1 = _make("r")
    r2 = _make("r")
    r1.merge_packed(packed, ids)
    r2.merge_packed(back, ids)
    for l1, l2 in zip(r1.store, r2.store):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert r1.canonical_time == r2.canonical_time


# -------------------------------------------- frame layer regressions


def _recv_thread(sock, out):
    out.append(recv_bytes_frame(sock))


def test_multidim_memoryview_frames_by_nbytes():
    """Regression for the flat-cast trap: ``len()`` of a 2-D
    memoryview counts first-dimension ELEMENTS, so sizing the length
    prefix with it truncates the frame. A 2-D sem-style lane must
    frame all of its nbytes."""
    lane = np.arange(48, dtype=np.uint8).reshape(4, 12)
    mv = memoryview(lane)
    assert len(mv) == 4 and mv.nbytes == 48       # the trap, on record
    a, b = socket.socketpair()
    try:
        out = []
        t = threading.Thread(target=_recv_thread, args=(b, out))
        t.start()
        send_bytes_frame(a, [mv])
        t.join(5)
        assert out and out[0] == lane.tobytes()
    finally:
        a.close()
        b.close()


def test_codec_encode_sizes_multidim_bodies_by_nbytes():
    """`FrameCodec.encode`'s compress threshold and the zlib feed both
    consume buffer pieces via nbytes — a 2-D body above the threshold
    compresses and round-trips intact."""
    c = FrameCodec(compress=True, min_compress_bytes=64)
    body = np.zeros((8, 128), np.uint8)            # 1024 compressible B
    pieces = c.encode([memoryview(body)])
    assert pieces[0] == FrameCodec.TAG_ZLIB
    joined = b"".join(bytes(p) for p in pieces)
    assert c.decode(joined) == body.tobytes()


def test_vectored_send_many_buffers_loopback():
    """One frame scattered over many small views (the shape the arena
    pack emits) survives the vectored `sendmsg` path, partial sends
    and all."""
    rng = np.random.default_rng(7)
    parts = [rng.integers(0, 256, size=n, dtype=np.uint8)
             for n in (0, 3, 8192, 1, 65536, 0, 17)]
    a, b = socket.socketpair()
    try:
        out = []
        t = threading.Thread(target=_recv_thread, args=(b, out))
        t.start()
        send_bytes_frame(a, [memoryview(p) for p in parts])
        t.join(5)
        assert out and out[0] == b"".join(p.tobytes() for p in parts)
    finally:
        a.close()
        b.close()


# --------------------------------------------------- pack cache bound


def test_pack_cache_bounded_under_watermark_churn():
    """A churn storm — 100 rounds each advancing the canonical time —
    must leave the cache at its depth bound, with every overflow
    recorded in the evictions counter."""
    from crdt_tpu.hlc import Hlc
    ev = default_registry().counter(
        "crdt_tpu_pack_cache_evictions_total",
        "pack_since cache entries LRU-evicted at the "
        "PACK_CACHE_SLOTS depth bound")
    c = _make("churn")
    c.put_batch(list(range(8)), list(range(8)))
    before = ev.value(node="churn")
    # 100 peers at 100 distinct watermarks against one static store:
    # every `since` is a fresh cache key (a local write would instead
    # CLEAR the cache — invalidation, not eviction).
    for i in range(100):
        c.pack_since(Hlc(BASE - 1000 + i, 0, "peer"))
        assert len(c._pack_cache) <= c.PACK_CACHE_SLOTS
    assert ev.value(node="churn") >= before + (100 - c.PACK_CACHE_SLOTS)


# ------------------------------------------------- fused merge+repack


def test_merge_and_repack_matches_two_dispatch_path():
    """The fused relay must be observationally identical to
    `merge_packed` + `pack_since`: same store lanes, same canonical,
    bit-identical packed output."""
    src = _make("src")
    src.put_batch([2, 9, 30], [20, 90, 300])
    src.delete_batch([9])
    packed, ids = src.pack_since(None)

    fused = _make("r")
    plain = _make("r")
    for r in (fused, plain):
        r.put_batch([1], [11])
    watermark = fused.canonical_time
    assert watermark == plain.canonical_time

    out_f, ids_f = fused.merge_and_repack(packed, ids, since=watermark)
    plain.merge_packed(packed, ids)
    out_p, ids_p = plain.pack_since(watermark)

    for l1, l2 in zip(fused.store, plain.store):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert fused.canonical_time == plain.canonical_time
    assert ids_f == ids_p
    for a, b in zip(out_f, out_p):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)


def test_merge_and_repack_seeds_next_round_pack_cache():
    """The fused dispatch caches the NEXT round's pack under the
    round's watermark — the follow-up `pack_since` is a hit returning
    the very same object, with no new dispatch."""
    hits = default_registry().counter("crdt_tpu_pack_cache_total", "")
    fused_ctr = default_registry().counter(
        "crdt_tpu_fused_repack_total",
        "gossip relays served by the fused merge+repack dispatch")
    src = _make("src2")
    src.put_batch([4, 5], [44, 55])
    packed, ids = src.pack_since(None)

    r = _make("relay")
    r.put_batch([0], [7])
    watermark = r.canonical_time
    f0 = fused_ctr.value(node="relay")
    seeded, _ = r.merge_and_repack(packed, ids, since=watermark)
    assert fused_ctr.value(node="relay") == f0 + 1
    h0 = hits.value(outcome="hit", node="relay")
    again, _ = r.pack_since(watermark)
    assert hits.value(outcome="hit", node="relay") == h0 + 1
    assert again is seeded                      # the exact seeded object
    # The seeded pack is what a peer at `watermark` needs: the rows
    # merged this round plus the relay's own at-watermark write (the
    # bound is inclusive, map_crdt.dart:44-45).
    assert set(seeded.slots.tolist()) == {0, 4, 5}


def test_merge_and_repack_empty_delta_falls_back():
    """k == 0 takes the fallback (`pack_since`), not the fused kernel —
    and still returns a well-formed (possibly empty) delta."""
    r = _make("fb")
    empty = PackedDelta(slots=np.empty(0, np.int32),
                        lt=np.empty(0, np.int64),
                        node=np.empty(0, np.int32),
                        val=np.empty(0, np.int64),
                        tomb=np.empty(0, np.uint8))
    out, ids = r.merge_and_repack(empty, [], since=None)
    assert out.k == 0 and ids == ["fb"]


# ------------------------------------------- single-dispatch ingest


def test_pallas_interpret_ingest_flush_matches_xla():
    """The touched-tile Mosaic scatter (interpret mode off-TPU) commits
    the identical store the lax scatter does."""
    from crdt_tpu.ops.pallas_merge import TILE
    a = DenseCrdt("i", n_slots=TILE, wall_clock=FakeClock(start=BASE),
                  executor="pallas-interpret")
    b = DenseCrdt("i", n_slots=TILE, wall_clock=FakeClock(start=BASE),
                  executor="xla")
    assert a._use_pallas_scatter() and not b._use_pallas_scatter()
    for c in (a, b):
        with c.ingest() as wc:
            c.put_batch([0, 1, TILE - 1], [10, 11, 12])
            c.put_batch([1, 700], [13, 14], tombs=[False, True])
        assert wc.flushes >= 1
    for l1, l2 in zip(a.store, b.store):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert a.canonical_time == b.canonical_time


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_sharded_ingest_flush_matches_plain():
    """The one-shard_map-program ingest commit matches the plain
    replica's flush bit for bit (occupied lanes)."""
    mesh = make_fanin_mesh(2, 4)
    sharded = ShardedDenseCrdt("s", N, mesh,
                               wall_clock=FakeClock(start=BASE))
    plain = DenseCrdt("s", N, wall_clock=FakeClock(start=BASE))
    for c in (sharded, plain):
        with c.ingest():
            c.put_batch(list(range(0, N, 5)), list(range(0, N, 5)))
            c.put_batch([0, 7], [100, 200], tombs=[True, False])
    occ = np.asarray(plain.store.occupied)
    np.testing.assert_array_equal(np.asarray(sharded.store.occupied),
                                  occ)
    for l1, l2 in zip(sharded.store, plain.store):
        np.testing.assert_array_equal(np.asarray(l1)[occ],
                                      np.asarray(l2)[occ])
    assert sharded.get(0) is None and sharded.get(7) == 200

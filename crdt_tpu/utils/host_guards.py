"""Vectorized host-side Hlc.recv fold — shared by the columnar
host backends (`TpuMapCrdt`, `SqliteCrdt`).

The reference's merge runs ``Hlc.recv`` per record in payload visit
order (crdt.dart:82, hlc.dart:80-97); its fast path shields records
the running canonical clock already dominates (hlc.dart:85). On
columns that collapses to: running = exclusive cummax of the packed
logical times (seeded with the canonical), a record is "slow" iff it
exceeds the running clock, and only slow records face the
duplicate-node / drift guards. One implementation here, so the two
host backends cannot drift on guard semantics.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from ..hlc import MAX_DRIFT, SHIFT

_NEG = -(2 ** 62)


class RecvFold(NamedTuple):
    """Result of the vectorized recv fold over a payload column."""
    new_canonical: int            # max(canonical, lt.max())
    bad_index: Optional[int]      # first offender, or None
    bad_is_dup: bool              # duplicate-node (vs drift) there
    canonical_at_fail: int        # running clock just BEFORE the offender


def recv_fold_columns(lt: np.ndarray, local_mask: np.ndarray,
                      canonical_lt: int, wall: int) -> RecvFold:
    """Fold ``Hlc.recv`` over packed logical times in visit order.

    ``local_mask`` marks records bearing THIS replica's node id (the
    duplicate-node candidates). Returns the post-absorption canonical
    and, if a guard trips, the first offender's index plus the
    partially-advanced canonical the sequential path would leave
    behind (crdt.dart:77-94 throw path). Raising is the caller's job —
    exception payloads need the caller's node id / typed context.
    """
    running = np.maximum(canonical_lt, np.concatenate(
        ([_NEG], np.maximum.accumulate(lt)[:-1])))
    slow = lt > running
    if slow.any():
        dup = slow & local_mask
        drift = slow & ~dup & ((lt >> SHIFT) - wall > MAX_DRIFT)
        bad = dup | drift
        if bad.any():
            i = int(np.argmax(bad))
            return RecvFold(new_canonical=0, bad_index=i,
                            bad_is_dup=bool(dup[i]),
                            canonical_at_fail=int(running[i]))
    return RecvFold(new_canonical=max(canonical_lt, int(lt.max())),
                    bad_index=None, bad_is_dup=False, canonical_at_fail=0)

"""Device-runtime dispatch ledger: what did the hardware actually do?

Every jit-cached device entry point in the tree — the ingest commit
scatters (XLA, Pallas and sharded routes), the merge joins
(sparse/wire/fused-repack/typed), the fan-in executors, the digest
reduction and the pack masks — reports each *dispatch* (one host call
that hands a program to the backend) to the process-wide
`DispatchLedger`. The ledger turns the fast-path invariants from
test-only assertions into runtime-observable facts
(docs/FASTPATH.md, docs/ANTIENTROPY.md):

- a pack-cache or digest-cache hit performs **zero** dispatches — the
  per-kernel counters do not move;
- a fused merge+repack (`merge_and_repack`) performs **exactly one**
  (`dense.merge_repack_step`);
- a write-combiner flush tick performs **exactly one** commit scatter.

Exposition (all on the default `MetricsRegistry`, so they ride the
``metrics`` wire op and the Prometheus renderer for free):

``crdt_tpu_device_dispatches_total{kernel}``
    dispatches per kernel entry point.
``crdt_tpu_device_dispatch_seconds{kernel}``
    wall time of the dispatching host call (log2 buckets). Dispatch
    is asynchronous on accelerators — this is enqueue + host prep
    time, not device execution time; fence-inclusive numbers live in
    the benches.
``crdt_tpu_device_compiles_total{kernel,bucket}``
    first-call events per (kernel, pow2 size bucket): callers pad
    batch dims to powers of two precisely so the jit cache sees O(log)
    distinct shapes, and the first call into a fresh bucket is the one
    that pays XLA compilation. Subsequent calls in the bucket are
    cache hits (``dispatches_total - compiles_total`` per kernel).
    Donation/sharding variants of one kernel can retrace within a
    bucket; the census counts the shape ladder, the dominant term.
``crdt_tpu_device_donation_violations_total{kernel}``
    donated input buffers still live after a donating dispatch —
    checked only on backends that honor donation (TPU/GPU; CPU ignores
    donation by design, jax warns and keeps the buffer).
``crdt_tpu_store_bytes{backend}``
    store-lane byte census at the last commit/merge that reported one.

The recording fast path is a class-based context manager (two
``perf_counter`` reads, one dict update under the ledger lock, one
counter inc, one histogram observe — single-digit microseconds against
dispatch costs of 100 µs+). ``default_ledger().enabled = False``
short-circuits ``record()`` to a shared no-op so the bench suite can
measure the ledger's own overhead differentially
(``ledger_overhead_frac`` in ``bench.py --mode ingest/--mode sync``,
budget 5%).

Kernels *register* (by name) at module import of the instrumented
module, independent of ever dispatching — the crdtlint
``dispatch-ledger-unregistered`` gate imports the ops/parallel modules
and verifies the required set against ``registered_kernels()``.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..analysis.concurrency import make_lock
from .registry import MetricsRegistry, default_registry

# Resolved once per process: donation-violation checks only make sense
# on backends that honor donation, and the census gauge labels bytes by
# the backend that holds them.
_BACKEND: Optional[str] = None


def _backend() -> str:
    global _BACKEND
    if _BACKEND is None:
        try:
            import jax
            _BACKEND = jax.default_backend()
        except Exception:          # pragma: no cover - jax always here
            _BACKEND = "unknown"
    return _BACKEND


def pow2_bucket(dim: Optional[int]) -> str:
    """The compile-census bucket label for a leading batch dim: the
    pow2 ceiling (the shape ladder callers pad onto), or ``"scalar"``
    for kernels with no size-varying dim."""
    if dim is None:
        return "scalar"
    d = max(int(dim), 1)
    return str(1 << (d - 1).bit_length())


class DispatchLedger:
    """Per-kernel dispatch accounting over one `MetricsRegistry`.

    Thread-safe: merges, gossip rounds and serving-tier flushes
    dispatch from different threads into the same ledger.
    """

    # crdtlint lock-discipline contract (obs.registry module docstring).
    _CRDTLINT_GUARDED = {"_lock": ("_counts", "_compiled", "_registered")}
    # analysis/concurrency.py: the ledger lock releases before the
    # metric incs (`_dispatch`), so nothing ever nests inside it.
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None \
            else default_registry()
        self._lock = make_lock("DispatchLedger._lock", 84)
        self._counts: Dict[str, int] = {}    # kernel -> dispatches
        self._compiled: set = set()          # (kernel, bucket) seen
        self._registered: set = set()        # kernel names declared
        self._metrics = None
        # Plain attribute, read unlocked on the hot path: toggling is
        # a coarse A/B switch (bench overhead measurement), not a
        # synchronization point.
        self.enabled = True

    def _instruments(self):
        m = self._metrics
        if m is None:
            reg = self._registry
            m = self._metrics = (
                reg.counter("crdt_tpu_device_dispatches_total",
                            "device dispatches by kernel entry point"),
                reg.histogram("crdt_tpu_device_dispatch_seconds",
                              "dispatching host-call wall time by "
                              "kernel (enqueue + host prep; async on "
                              "accelerators)"),
                reg.counter("crdt_tpu_device_compiles_total",
                            "first-call events per (kernel, pow2 size "
                            "bucket) — the compile census"),
                reg.counter("crdt_tpu_device_donation_violations_total",
                            "donated inputs still live after a "
                            "donating dispatch (TPU/GPU only)"),
                reg.gauge("crdt_tpu_store_bytes",
                          "store-lane bytes at the last reported "
                          "commit/merge, by backend"),
            )
        return m

    # --- registration (the crdtlint completeness surface) ---

    def register(self, *kernels: str) -> None:
        """Declare kernel entry points as ledger-instrumented. Called
        at module import of the instrumented module, so the
        `dispatch-ledger-unregistered` gate can verify coverage
        without dispatching anything."""
        with self._lock:
            self._registered.update(kernels)

    def registered_kernels(self) -> frozenset:
        with self._lock:
            return frozenset(self._registered)

    # --- reads (tests and invariant probes) ---

    def dispatches(self, kernel: Optional[str] = None) -> int:
        """Host-side dispatch count for one kernel, or the total over
        every kernel — the number a zero-dispatch invariant probe
        snapshots before and after the operation under test."""
        with self._lock:
            if kernel is not None:
                return self._counts.get(kernel, 0)
            return sum(self._counts.values())

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._counts)

    # --- recording ---

    def record(self, kernel: str, dim: Optional[int] = None,
               donated=None):
        """Context manager timing ONE dispatch of ``kernel``.

        ``dim`` is the compile-relevant leading batch dim (bucketed to
        its pow2 ceiling for the compile census); ``donated`` is a
        representative donated input array (one lane is enough — XLA
        donates the whole tree or none of it) checked post-call for
        donation violations on backends that honor donation."""
        if not self.enabled:
            return _NULL_RECORD
        return _Record(self, kernel, dim, donated)

    def _dispatch(self, kernel: str, seconds: float,
                  dim: Optional[int], donated) -> None:
        bucket = pow2_bucket(dim)
        disp_c, disp_h, comp_c, viol_c, _ = self._instruments()
        first = False
        with self._lock:
            self._counts[kernel] = self._counts.get(kernel, 0) + 1
            if (kernel, bucket) not in self._compiled:
                self._compiled.add((kernel, bucket))
                first = True
        disp_c.inc(kernel=kernel)
        disp_h.observe(seconds, kernel=kernel)
        if first:
            comp_c.inc(kernel=kernel, bucket=bucket)
        if donated is not None and _backend() in ("tpu", "gpu"):
            try:
                deleted = donated.is_deleted()
            except Exception:
                deleted = True     # can't tell — don't cry wolf
            if not deleted:
                viol_c.inc(kernel=kernel)

    # --- store census ---

    def census(self, store) -> int:
        """Report a store's lane bytes to the per-backend gauge.
        ``store`` is any NamedTuple of arrays (`DenseStore` & friends);
        ``nbytes`` is array metadata, so this costs no device work."""
        nbytes = 0
        for lane in store:
            nbytes += int(getattr(lane, "nbytes", 0) or 0)
        if self.enabled:
            self._instruments()[4].set(float(nbytes),
                                       backend=_backend())
        return nbytes


class _Record:
    __slots__ = ("_ledger", "_kernel", "_dim", "_donated", "_t0")

    def __init__(self, ledger: DispatchLedger, kernel: str,
                 dim: Optional[int], donated):
        self._ledger = ledger
        self._kernel = kernel
        self._dim = dim
        self._donated = donated

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._ledger._dispatch(self._kernel,
                                   time.perf_counter() - self._t0,
                                   self._dim, self._donated)
        return False


class _NullRecord:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_RECORD = _NullRecord()

_DEFAULT_LEDGER = DispatchLedger()


def default_ledger() -> DispatchLedger:
    """The process-wide ledger every instrumented entry point reports
    to (same singleton discipline as `default_registry`)."""
    return _DEFAULT_LEDGER


def register(*kernels: str) -> None:
    _DEFAULT_LEDGER.register(*kernels)


def record(kernel: str, dim: Optional[int] = None, donated=None):
    """Module-level fast path for instrumented call sites: resolves
    the singleton once and short-circuits to a shared no-op context
    manager when the ledger is disabled."""
    led = _DEFAULT_LEDGER
    if not led.enabled:
        return _NULL_RECORD
    return led.record(kernel, dim, donated)


def census(store) -> int:
    return _DEFAULT_LEDGER.census(store)

"""SqliteCrdt: the persistent-backend plugin pattern (README.md:39).

Runs the exported conformance kit against the SQLite backend, then the
persistence-specific behaviors the in-memory backends can't exhibit:
resume-from-disk clock rebuild (crdt.dart:31-33, 114-121), indexed
delta queries, wire interop with the other backends, and custom value
codecs (record.dart:3-9).
"""

import json

from conformance import CrdtConformance, FakeClock

from crdt_tpu import Hlc, MapCrdt, Record, SqliteCrdt, sync


class TestSqliteConformance(CrdtConformance):
    def make_crdt(self):
        return SqliteCrdt(self.node_id, wall_clock=FakeClock())


class TestPersistence:
    def test_resume_from_disk(self, tmp_path):
        db = str(tmp_path / "replica.db")
        with SqliteCrdt("nodeA", db, wall_clock=FakeClock()) as a:
            a.put("x", 1)
            a.put("y", {"nested": [1, 2]})
            a.delete("x")
            canonical = a.canonical_time

        with SqliteCrdt("nodeA", db, wall_clock=FakeClock()) as b:
            # Clock rebuilt from MAX(lt) (crdt.dart:114-121): same
            # logical time, local node id.
            assert b.canonical_time.logical_time == canonical.logical_time
            assert b.map == {"y": {"nested": [1, 2]}}
            assert b.is_deleted("x") is True
            # Post-resume writes advance past everything stored.
            b.put("z", 3)
            assert b.get_record("z").hlc > b.get_record("y").hlc

    def test_hlc_string_roundtrip_exact(self, tmp_path):
        db = str(tmp_path / "replica.db")
        with SqliteCrdt("nodeA", db, wall_clock=FakeClock()) as a:
            a.put("k", 42)
            rec = a.get_record("k")
        with SqliteCrdt("nodeA", db, wall_clock=FakeClock()) as b:
            got = b.get_record("k")
        assert got.hlc == rec.hlc
        assert got.modified == rec.modified
        assert got == rec

    def test_delta_query_inclusive_bound(self):
        crdt = SqliteCrdt("nodeA", wall_clock=FakeClock())
        crdt.put("x", 1)
        t = crdt.canonical_time
        assert set(crdt.record_map(modified_since=t)) == {"x"}
        crdt.put("y", 2)
        later = crdt.get_record("y").modified
        assert set(crdt.record_map(modified_since=later)) == {"y"}

    def test_sync_with_other_backends(self):
        clk = FakeClock()
        durable = SqliteCrdt("dur", wall_clock=clk)
        mem = MapCrdt("mem", wall_clock=clk)
        durable.put("a", 1)
        mem.put("b", 2)
        mem.delete("b")
        sync(durable, mem)
        assert durable.map == mem.map == {"a": 1}
        assert durable.is_deleted("b") is True

    def test_wire_json_roundtrip(self):
        clk = FakeClock()
        a = SqliteCrdt("nodeA", wall_clock=clk)
        a.put("k", "v")
        b = MapCrdt("nodeB", wall_clock=clk)
        b.merge_json(a.to_json())
        assert b.get("k") == "v"
        # And back into a THIRD sqlite replica via b's wire output.
        c = SqliteCrdt("nodeC", wall_clock=clk)
        c.merge_json(b.to_json())
        assert c.get("k") == "v"
        assert c.get_record("k").hlc == a.get_record("k").hlc

    def test_custom_value_codec(self):
        class Point:
            def __init__(self, x, y):
                self.x, self.y = x, y

            def __eq__(self, other):
                return (self.x, self.y) == (other.x, other.y)

        crdt = SqliteCrdt(
            "nodeA", wall_clock=FakeClock(),
            value_encoder=lambda p: {"x": p.x, "y": p.y},
            value_decoder=lambda d: Point(d["x"], d["y"]))
        crdt.put("p", Point(3, 4))
        assert crdt.get("p") == Point(3, 4)

    def test_custom_key_codec(self):
        crdt = SqliteCrdt(
            "nodeA", wall_clock=FakeClock(),
            key_encoder=lambda k: json.dumps(k),
            key_decoder=lambda s: tuple(json.loads(s)))
        crdt.put((1, 2), "v")
        assert crdt.map == {(1, 2): "v"}
        assert crdt.contains_key((1, 2))

    def test_merge_updates_disk_not_just_memory(self, tmp_path):
        db = str(tmp_path / "replica.db")
        clk = FakeClock()
        remote = MapCrdt("remote", wall_clock=clk)
        remote.put("r", 9)
        with SqliteCrdt("dur", db, wall_clock=clk) as a:
            a.merge(remote.record_map())
        with SqliteCrdt("dur", db, wall_clock=clk) as b:
            assert b.get("r") == 9

    def test_watch_emits_on_merge(self):
        clk = FakeClock()
        crdt = SqliteCrdt("dur", wall_clock=clk)
        stream = crdt.watch().record()
        remote = MapCrdt("remote", wall_clock=clk)
        remote.put("m", 5)
        crdt.merge(remote.record_map())
        assert ("m", 5) in {(e.key, e.value) for e in stream.events}

    def test_delta_merge_uses_keyed_lookup(self):
        # merge consults only the delta's keys (O(delta), not O(table));
        # >500 keys exercises the host-parameter batching.
        clk = FakeClock()
        crdt = SqliteCrdt("dur", wall_clock=clk)
        crdt.put_all({f"k{i}": i for i in range(1200)})
        remote = MapCrdt("remote", wall_clock=clk)
        remote.put_all({f"k{i}": -i for i in range(0, 1200, 2)})
        remote.put("new", 1)
        crdt.merge(remote.record_map())
        assert crdt.get("k0") == 0 or crdt.get("k0") == -0
        assert crdt.get("k2") == -2      # newer remote write wins
        assert crdt.get("k3") == 3       # untouched key intact
        assert crdt.get("new") == 1
        # Losing delta: older records change nothing.
        seen = {k: (r.hlc, r.value) for k, r in crdt.record_map().items()}
        crdt.merge({k: r for k, r in remote.record_map().items()})
        again = {k: (r.hlc, r.value) for k, r in crdt.record_map().items()}
        assert seen == again

    def test_int_node_id_roundtrips_typed(self, tmp_path):
        # Node ids persist as text; resume must restore them with the
        # node_id's type so tie-breaks and dup detection keep working.
        db = str(tmp_path / "replica.db")
        clk = FakeClock()
        with SqliteCrdt(7, db, wall_clock=clk) as a:
            a.put("x", 1)
        with SqliteCrdt(7, db, wall_clock=clk) as b:
            assert b.get_record("x").hlc.node_id == 7  # int, not "7"
            # Tie-break against another int node must not TypeError.
            h = b.get_record("x").hlc
            remote = Record(Hlc(h.millis, h.counter, 9), 99,
                            Hlc(h.millis, h.counter, 9))
            b.merge({"x": remote})
            assert b.get("x") == 99  # 9 > 7 wins the tie

    def test_purge_clears_disk(self, tmp_path):
        db = str(tmp_path / "replica.db")
        with SqliteCrdt("dur", db, wall_clock=FakeClock()) as a:
            a.put("x", 1)
            a.clear(purge=True)
        with SqliteCrdt("dur", db, wall_clock=FakeClock()) as b:
            assert b.record_map() == {}
            assert b.canonical_time.logical_time == 0


def test_columnar_ingest_matches_generic_rows():
    """The columnar merge_json and the generic object path must leave
    identical record state — including LWW losers against existing
    rows, logicalTime ties broken by node id, tombstones, and the
    canonical clock."""
    import os

    from crdt_tpu import MapCrdt
    from crdt_tpu.testing import FakeClock

    src = MapCrdt("remote", wall_clock=FakeClock(start=1_700_000_000_000))
    src.put_all({f"k{i}": {"v": i} if i % 3 else None for i in range(50)})
    src.put("tie", 1)
    wire = src.to_json()

    def build(force_generic):
        clk = FakeClock(start=1_700_000_000_500)
        c = SqliteCrdt("local", wall_clock=clk)
        c.put_all({f"k{i}": "mine" for i in range(0, 50, 5)})
        if force_generic:
            import crdt_tpu.native as native_mod
            orig = native_mod.load
            native_mod.load = lambda: None
            try:
                c.merge_json(wire)
            finally:
                native_mod.load = orig
        else:
            c.merge_json(wire)
        return c

    fast, slow = build(False), build(True)
    assert fast.record_map() == slow.record_map()
    assert fast.canonical_time == slow.canonical_time
    assert fast.to_json() == slow.to_json()


def test_columnar_ingest_tick_parity_with_oracle():
    from crdt_tpu import MapCrdt
    from crdt_tpu.testing import CountingClock, FakeClock
    src = MapCrdt("remote", wall_clock=FakeClock())
    src.put_all({"x": 1, "y": None})
    co, cs = CountingClock(), CountingClock()
    oracle = MapCrdt("abc", wall_clock=co)
    lite = SqliteCrdt("abc", wall_clock=cs)
    for payload in (src.to_json(), "{}"):
        oracle.merge_json(payload)
        lite.merge_json(payload)
        assert co.reads == cs.reads
    assert oracle.to_json() == lite.to_json()


def test_wal_mode_survives_restart(tmp_path):
    db = str(tmp_path / "replica.db")
    from crdt_tpu.testing import FakeClock
    c = SqliteCrdt("n1", db, wall_clock=FakeClock())
    assert c._conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    c.put_all({"a": 1, "b": 2})
    c.delete("a")
    wire = c.to_json()
    c.close()
    r = SqliteCrdt("n1", db, wall_clock=FakeClock())
    assert r.to_json() == wire
    assert r.map == {"b": 2}
    r.close()


def test_columnar_ingest_stores_canonical_hlc_strings():
    """Lowercase counter hex on the wire parses fine but is NOT
    byte-canonical; the columnar path must store the canonical %04X
    form exactly like the generic path."""
    from crdt_tpu.testing import FakeClock
    wire = ('{"a":{"hlc":"2023-05-06T07:08:09.123Z-00ab-peer",'
            '"value":1}}')
    c = SqliteCrdt("local", wall_clock=FakeClock())
    c.merge_json(wire)
    (stored,) = c._conn.execute(
        "SELECT hlc FROM records WHERE key='a'").fetchone()
    assert stored == "2023-05-06T07:08:09.123Z-00AB-peer"

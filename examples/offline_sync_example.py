"""Offline-first sync: a durable edge replica, a device-backed hub,
and an async UI consumer — the reference's deployment story
(README.md:39 persistent backends + example/crdt_example.dart wire
exchange) on this framework's backends.

- The EDGE node is a `SqliteCrdt`: writes survive restarts; resuming
  is just reopening the file (crdt.dart:31-33 refreshCanonicalTime).
- The HUB is a `TpuMapCrdt`: the same `Crdt` surface with merges
  running on the accelerator.
- Sync is the reference's anti-entropy round (full push + inclusive
  delta pull, test/map_crdt_test.dart:273-279) over the JSON wire.
- The "UI" consumes `watch().aiter()` — the Dart `await for` shape.

Run: python examples/offline_sync_example.py
"""

import asyncio
import os
import tempfile

from crdt_tpu import SqliteCrdt, TpuMapCrdt, sync_json


async def main() -> None:
    db = os.path.join(tempfile.mkdtemp(), "edge.db")

    # --- day 1: the edge writes offline, then goes away ---
    with SqliteCrdt("edge-1", db) as edge:
        edge.put("cart:apples", 3)
        edge.put("cart:pears", 2)
        edge.delete("cart:pears")
    print("edge wrote offline and shut down")

    # --- the hub accumulates state from another replica meanwhile ---
    hub = TpuMapCrdt("hub")
    hub.put("cart:plums", 7)

    # --- day 2: the edge comes back and syncs over the JSON wire ---
    edge = SqliteCrdt("edge-1", db)   # resume: clock rebuilt from disk
    ui_events = []

    async def ui():
        async with edge.watch().aiter() as stream:
            async for event in stream:
                ui_events.append(f"{event.key} -> {event.value}")

    ui_task = asyncio.ensure_future(ui())
    await asyncio.sleep(0)            # let the UI subscribe

    sync_json(edge, hub)              # full push + inclusive delta pull
    await asyncio.sleep(0.05)

    print(f"edge map:  {dict(sorted(edge.map.items()))}")
    print(f"hub map:   {dict(sorted(hub.map.items()))}")
    assert edge.map == hub.map == {"cart:apples": 3, "cart:plums": 7}
    assert hub.is_deleted("cart:pears") is True  # tombstone propagated
    print(f"ui saw:    {sorted(ui_events)}")

    ui_task.cancel()
    edge.close()


if __name__ == "__main__":
    asyncio.run(main())

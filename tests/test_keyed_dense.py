"""Conformance kit over the dense models (VERDICT r3 item 5).

`KeyedDenseCrdt` adapts arbitrary keys onto dense slots so
`DenseCrdt` and `ShardedDenseCrdt` run the SAME 21-test behavioral
suite as every record-dict backend — one contract, every backend
(test/crdt_test.dart:7-11). The array-surface extras stay in
tests/test_dense_crdt.py / test_sharded_dense_crdt.py.
"""

import pytest

from conformance import CrdtConformance, FakeClock
from crdt_tpu import DenseCrdt, KeyedDenseCrdt, MapCrdt, ShardedDenseCrdt
from crdt_tpu.parallel import make_fanin_mesh


class TestDenseConformance(CrdtConformance):
    def make_crdt(self):
        return KeyedDenseCrdt(
            DenseCrdt("abc", 64, wall_clock=FakeClock()))


class TestShardedDenseConformance(CrdtConformance):
    def make_crdt(self):
        return KeyedDenseCrdt(ShardedDenseCrdt(
            "abc", 64, make_fanin_mesh(2, 4), wall_clock=FakeClock()))


class TestDensePallasInterpretConformance(CrdtConformance):
    """The Mosaic executor path under the interpreter (no TPU in CI):
    the kit exercises merge/put/watch through the kernel dispatch."""

    def make_crdt(self):
        from crdt_tpu.ops.pallas_merge import TILE
        return KeyedDenseCrdt(DenseCrdt(
            "abc", TILE, wall_clock=FakeClock(),
            executor="pallas-interpret"))


def test_adapter_differential_vs_oracle():
    """Random op sequence: adapter-over-dense vs the scalar oracle,
    byte-identical wire export at every step."""
    import random
    rng = random.Random(7)
    clk_a, clk_b = FakeClock(), FakeClock()
    oracle = MapCrdt("abc", wall_clock=clk_a)
    dense = KeyedDenseCrdt(DenseCrdt("abc", 256, wall_clock=clk_b))
    keys = [f"k{i}" for i in range(32)]
    for step in range(120):
        op = rng.random()
        k = rng.choice(keys)
        if op < 0.5:
            v = rng.randrange(1000)
            oracle.put(k, v)
            dense.put(k, v)
        elif op < 0.7:
            oracle.delete(k)
            dense.delete(k)
        elif op < 0.9:
            batch = {rng.choice(keys): (None if rng.random() < 0.3
                                        else rng.randrange(1000))
                     for _ in range(rng.randrange(1, 6))}
            oracle.put_all(dict(batch))
            dense.put_all(dict(batch))
        else:
            src = MapCrdt(f"peer{step}", wall_clock=FakeClock(
                start=1_700_000_000_000 + step))
            src.put_all({rng.choice(keys): rng.randrange(1000)
                         for _ in range(rng.randrange(1, 4))})
            recs = src.record_map()
            oracle.merge(dict(recs))
            dense.merge(dict(recs))
        assert oracle.to_json() == dense.to_json(), f"diverged at {step}"
    assert oracle.map == dense.map


def test_put_records_preserves_stamps():
    from crdt_tpu import Hlc, Record
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=FakeClock()))
    h = Hlc(1_700_000_000_123, 5, "zed")
    m = Hlc(1_700_000_000_456, 6, "abc")
    before = kc.canonical_time
    kc.put_records({"x": Record(h, 42, m)})
    rec = kc.get_record("x")
    assert rec.hlc == h and rec.modified == m and rec.value == 42
    # putRecords stores without updating the HLC (crdt.dart:151-155)
    assert kc.canonical_time == before


def test_mixed_put_all_single_stamp():
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=FakeClock()))
    kc.put("y", 9)
    kc.put_all({"a": 1, "b": None, "c": 3})
    ra, rb, rc = (kc.get_record(k) for k in "abc")
    assert ra.hlc == rb.hlc == rc.hlc       # ONE batch stamp
    assert rb.value is None and rb.is_deleted
    assert kc.map == {"y": 9, "a": 1, "c": 3}


def test_tick_parity_with_oracle_incl_empty_merge():
    """KeyedDenseCrdt consumes the same wall reads as the oracle —
    including the empty anti-entropy round (the normal no-change sync),
    where the dense model must spend the absorption read AND the send
    read like every record-dict backend."""
    from crdt_tpu.testing import CountingClock
    co, cd = CountingClock(), CountingClock()
    oracle = MapCrdt("abc", wall_clock=co)
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=cd))
    src = MapCrdt("peer", wall_clock=FakeClock(step=5))
    src.put_all({"x": 1, "y": 2})
    for payload in (src.to_json(), "{}"):
        oracle.merge_json(payload)
        kc.merge_json(payload)
        assert co.reads == cd.reads, (
            f"wall-read drift on {payload[:30]!r}: "
            f"{co.reads} vs {cd.reads}")
    oracle.put("z", 3)
    kc.put("z", 3)
    assert co.reads == cd.reads
    assert oracle.to_json() == kc.to_json()


def test_watch_survives_raw_dense_writes():
    """A raw write through `.dense` to a slot the adapter never
    interned must not blow up the forwarding subscription; the event
    passes through keyed by slot index."""
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=FakeClock()))
    stream = kc.watch().record()
    kc.put("x", 1)
    kc.dense.put_batch([50], [7])     # never interned
    kc.put("y", 2)
    assert [(e.key, e.value) for e in stream.events] == \
        [("x", 1), (50, 7), ("y", 2)]


def test_put_records_pads_to_stable_shapes():
    """put_slot_records pads batches to powers of two (sentinel slots
    dropped) — same jit-shape discipline as merge_records; verify
    odd-size batches land exactly and nothing leaks into other slots."""
    from crdt_tpu import Hlc, Record
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=FakeClock()))
    mk = lambda i: Record(Hlc(1_700_000_000_000 + i, 0, "n"), i,
                          Hlc(1_700_000_000_000 + i, 0, "abc"))
    kc.put_records({f"k{i}": mk(i) for i in range(5)})   # pads to 8
    kc.put_records({f"j{i}": mk(100 + i) for i in range(3)})  # pads to 4
    assert len(kc.record_map()) == 8
    assert kc.map == {**{f"k{i}": i for i in range(5)},
                      **{f"j{i}": 100 + i for i in range(3)}}


def test_record_map_survives_raw_dense_writes():
    """Raw `.dense` writes to never-interned slots surface keyed by
    slot index in record_map/map/to_json instead of crashing."""
    kc = KeyedDenseCrdt(DenseCrdt("abc", 64, wall_clock=FakeClock()))
    kc.put("x", 1)
    kc.dense.put_batch([10], [5])
    assert kc.map == {"x": 1, 10: 5}
    assert set(kc.record_map()) == {"x", 10}
    assert '"10"' in kc.to_json()


def test_adapter_auto_grows_past_capacity():
    """VERDICT r4 item 7: interning past n_slots grows the wrapped
    model (map_crdt.dart:10's unbounded growth) instead of raising."""
    kc = KeyedDenseCrdt(DenseCrdt("abc", 4, wall_clock=FakeClock()))
    kc.put_all({f"k{i}": i for i in range(11)})   # 4 -> 8 -> 16 slots
    assert kc.dense.n_slots == 16
    assert kc.map == {f"k{i}": i for i in range(11)}
    # Records kept their slots across the growth.
    assert kc.get_record("k0").value == 0
    # The pallas-forced executor keeps its tile alignment on growth.
    from crdt_tpu.ops.pallas_merge import TILE
    kp = KeyedDenseCrdt(DenseCrdt("abc", TILE, wall_clock=FakeClock(),
                                  executor="pallas-interpret"))
    kp.put_all({f"k{i}": 1 for i in range(TILE + 1)})
    assert kp.dense.n_slots == 2 * TILE


def test_adapter_growth_syncs_with_fixed_peer():
    """A grown adapter still syncs with a peer at the original
    capacity (narrower changesets pad on ingest)."""
    from crdt_tpu.models.dense_crdt import sync_dense
    a = KeyedDenseCrdt(DenseCrdt("na", 2, wall_clock=FakeClock()))
    b = DenseCrdt("nb", 8, wall_clock=FakeClock(start=1_700_000_000_050))
    a.put_all({f"k{i}": i * 10 for i in range(5)})   # grows to 8
    sync_dense(a.dense, b)
    assert b.get(0) == 0 and b.get(4) == 40

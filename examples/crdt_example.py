"""Quickstart: put -> to_json -> (mock wire) -> merge_json round trip.

Port of the reference `example/crdt_example.dart:1-25`.
"""

from crdt_tpu import Hlc, MapCrdt


def send_to_remote(json_str: str) -> str:
    """Mock sending the CRDT to a remote node and getting an update back.

    The remote stamps its write one wall tick later so the LWW merge
    deterministically adopts it (the Dart example relies on interpreter
    latency to cross the millisecond boundary).
    """
    import time
    time.sleep(0.002)
    hlc = Hlc.now("another_nodeId")
    return '{"a":{"hlc":"%s","value":2}}' % hlc


def main() -> None:
    crdt = MapCrdt("node_id")

    # Insert a record
    crdt.put("a", 1)
    # Read the record
    print(f"Record: {crdt.get('a')}")

    # Export the CRDT as Json
    json_str = crdt.to_json()
    print(f"Wire JSON: {json_str}")
    # Send to remote node
    remote_json = send_to_remote(json_str)
    # Merge remote CRDT with local
    crdt.merge_json(remote_json)
    # Verify updated record
    print(f"Record after merging: {crdt.get('a')}")


if __name__ == "__main__":
    main()

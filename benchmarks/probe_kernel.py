"""Kernel-variant probe: where does the fan-in kernel's time go?

Runs the headline shape through three kernel variants to split the
compute vs HBM budget:

- ``full``    — the production kernel (guards + join).
- ``nojoin``  — guards removed, join only (upper bound on guard cost).
- ``copy``    — no compute: stream cs + store through VMEM, write
  store back (the pure memory-bandwidth ceiling for this layout).

The variant kernels deliberately carry their own copies of the
pallas_call scaffolding: they exist to measure layout effects, so they
must be free to drift from the production geometry without touching it.

Usage: python benchmarks/probe_kernel.py [--keys N] [--replicas N]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (bench.py helpers)

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bench import make_changeset, _MILLIS
from crdt_tpu.hlc import SHIFT
from crdt_tpu.ops.dense import empty_dense_store
from crdt_tpu.ops.pallas_merge import (_SB, _LANE, _lex_gt, _split64,
                                       NEG_HI, pallas_fanin_step,
                                       pallas_fanin_stream,
                                       split_changeset, split_store)


def _join_only_kernel(scalars_ref,
                      cs_hi, cs_lo, cs_node, cs_vhi, cs_vlo, cs_tomb,
                      st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
                      st_mhi, st_mlo, st_mnode,
                      o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
                      o_mhi, o_mlo, o_mnode, win_ref):
    local_node = scalars_ref[2]
    newc_hi = scalars_ref[5]
    newc_lo = scalars_ref[6].astype(jnp.uint32)
    b_hi = st_hi[...]
    b_lo = st_lo[...]
    b_node = st_node[...]
    b_vhi = st_vhi[...]
    b_vlo = st_vlo[...]
    b_tomb = st_tomb[...]
    win = jnp.zeros(b_hi.shape, jnp.bool_)
    for r in range(cs_hi.shape[0]):
        hi = cs_hi[r]
        lo = cs_lo[r]
        node = cs_node[r]
        gt = _lex_gt(hi, lo, node, b_hi, b_lo, b_node)
        b_hi = jnp.where(gt, hi, b_hi)
        b_lo = jnp.where(gt, lo, b_lo)
        b_node = jnp.where(gt, node, b_node)
        b_vhi = jnp.where(gt, cs_vhi[r], b_vhi)
        b_vlo = jnp.where(gt, cs_vlo[r], b_vlo)
        b_tomb = jnp.where(gt, cs_tomb[r], b_tomb)
        win = win | gt
    o_hi[...] = b_hi
    o_lo[...] = b_lo
    o_node[...] = b_node
    o_vhi[...] = b_vhi
    o_vlo[...] = b_vlo
    o_tomb[...] = b_tomb
    o_mhi[...] = jnp.where(win, newc_hi, st_mhi[...])
    o_mlo[...] = jnp.where(win, newc_lo, st_mlo[...])
    o_mnode[...] = jnp.where(win, local_node, st_mnode[...])
    win_ref[...] = win.astype(jnp.int32)


def _copy_kernel(scalars_ref,
                 cs_hi, cs_lo, cs_node, cs_vhi, cs_vlo, cs_tomb,
                 st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
                 st_mhi, st_mlo, st_mnode,
                 o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
                 o_mhi, o_mlo, o_mnode, win_ref):
    r_last = cs_hi.shape[0] - 1
    # Touch every cs row so nothing is DCE'd, with one add per lane.
    a_hi = cs_hi[0]
    a_lo = cs_lo[0]
    for r in range(1, r_last + 1):
        a_hi = a_hi + cs_hi[r]
        a_lo = a_lo + cs_lo[r]
    o_hi[...] = st_hi[...] + a_hi
    o_lo[...] = st_lo[...] + a_lo
    o_node[...] = st_node[...] + cs_node[r_last]
    o_vhi[...] = st_vhi[...] + cs_vhi[r_last]
    o_vlo[...] = st_vlo[...] + cs_vlo[r_last]
    o_tomb[...] = st_tomb[...] + cs_tomb[r_last]
    o_mhi[...] = st_mhi[...]
    o_mlo[...] = st_mlo[...]
    o_mnode[...] = st_mnode[...]
    win_ref[...] = cs_node[r_last]


def _stream_noguard_kernel(n_chunks_ignored, scalars_ref,
                           cs_hi, cs_lo, cs_node, cs_vhi, cs_vlo, cs_tomb,
                           st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
                           st_mhi, st_mlo, st_mnode,
                           o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
                           o_mhi, o_mlo, o_mnode, win_ref):
    """The stream kernel's join with ALL guard work removed — isolates
    the guard cost inside the fused chunk loop."""
    c = pl.program_id(1)
    first = c == 0
    local_node = scalars_ref[2]
    off = (c << SHIFT).astype(jnp.uint32)
    b_hi = jnp.where(first, st_hi[...], o_hi[...])
    b_lo = jnp.where(first, st_lo[...], o_lo[...])
    b_node = jnp.where(first, st_node[...], o_node[...])
    b_vhi = jnp.where(first, st_vhi[...], o_vhi[...])
    b_vlo = jnp.where(first, st_vlo[...], o_vlo[...])
    b_tomb = jnp.where(first, st_tomb[...], o_tomb[...])
    win_prev = jnp.where(first, jnp.int32(0), win_ref[...])
    win = jnp.zeros(b_hi.shape, jnp.bool_)
    for r in range(cs_hi.shape[0]):
        hi0 = cs_hi[r]
        lo0 = cs_lo[r]
        node = cs_node[r]
        lo = lo0 + jnp.where(hi0 == NEG_HI, jnp.uint32(0), off)
        hi = hi0 + (lo < lo0).astype(jnp.int32)
        gt = _lex_gt(hi, lo, node, b_hi, b_lo, b_node)
        b_hi = jnp.where(gt, hi, b_hi)
        b_lo = jnp.where(gt, lo, b_lo)
        b_node = jnp.where(gt, node, b_node)
        b_vhi = jnp.where(gt, cs_vhi[r], b_vhi)
        b_vlo = jnp.where(gt, cs_vlo[r], b_vlo)
        b_tomb = jnp.where(gt, cs_tomb[r], b_tomb)
        win = win | gt
    o_hi[...] = b_hi
    o_lo[...] = b_lo
    o_node[...] = b_node
    o_vhi[...] = b_vhi
    o_vlo[...] = b_vlo
    o_tomb[...] = b_tomb
    o_mhi[...] = jnp.where(win, scalars_ref[5], st_mhi[...])
    o_mlo[...] = jnp.where(win, scalars_ref[6].astype(jnp.uint32),
                           st_mlo[...])
    o_mnode[...] = jnp.where(win, local_node, st_mnode[...])
    win_ref[...] = win_prev | win.astype(jnp.int32)


def _stream_call(kernel, store, cs, scalars, n_chunks):
    from functools import partial
    r, n = cs.hi.shape
    rows = n // _LANE
    _i32 = jnp.int32
    cs_spec = pl.BlockSpec((r, _SB, _LANE),
                           lambda i, c: (_i32(0), _i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((_SB, _LANE), lambda i, c: (_i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    st2d = [lane.reshape(rows, _LANE) for lane in store]
    cs3d = [lane.reshape(r, rows, _LANE) for lane in cs]
    out_shapes = (
        [jax.ShapeDtypeStruct((rows, _LANE), lane.dtype) for lane in st2d] +
        [jax.ShapeDtypeStruct((rows, _LANE), jnp.int32)])
    outs = pl.pallas_call(
        partial(kernel, n_chunks),
        grid=(rows // _SB, n_chunks),
        in_specs=([pl.BlockSpec((7,), lambda i, c: (_i32(0),),
                                memory_space=pltpu.SMEM)] +
                  [cs_spec] * 6 + [st_spec] * 9),
        out_specs=tuple([st_spec] * 10),
        out_shape=tuple(out_shapes),
        input_output_aliases={1 + 6 + j: j for j in range(9)},
    )(scalars, *cs3d, *st2d)
    return outs[0].reshape(n)


def _variant_call(kernel, store, cs, scalars):
    r, n = cs.hi.shape
    rows = n // _LANE
    _i32 = jnp.int32
    cs_spec = pl.BlockSpec((r, _SB, _LANE),
                           lambda i: (_i32(0), _i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((_SB, _LANE), lambda i: (_i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    st2d = [lane.reshape(rows, _LANE) for lane in store]
    cs3d = [lane.reshape(r, rows, _LANE) for lane in cs]
    out_shapes = (
        [jax.ShapeDtypeStruct((rows, _LANE), lane.dtype) for lane in st2d] +
        [jax.ShapeDtypeStruct((rows, _LANE), jnp.int32)])
    outs = pl.pallas_call(
        kernel,
        grid=(rows // _SB,),
        in_specs=([pl.BlockSpec((7,), lambda i: (_i32(0),),
                                memory_space=pltpu.SMEM)] +
                  [cs_spec] * 6 + [st_spec] * 9),
        out_specs=tuple([st_spec] * 10),
        out_shape=tuple(out_shapes),
        input_output_aliases={1 + 6 + j: j for j in range(9)},
    )(scalars, *cs3d, *st2d)
    return outs[0].reshape(n)


def run_variant(name: str, n_keys: int, n_replicas: int, chunk: int,
                repeats: int = 3) -> float:
    n_chunks = n_replicas // chunk
    store = split_store(empty_dense_store(n_keys))
    cs = split_changeset(make_changeset(chunk, n_keys, seed=0))
    canonical = jnp.int64(_MILLIS << SHIFT)
    wall = jnp.int64(_MILLIS + 10_000)

    if name == "full":
        @jax.jit
        def run(store, cs):
            def body(i, carry):
                st, canon = carry
                st2, res = pallas_fanin_step(st, cs, canon, jnp.int32(0),
                                             wall)
                return (st2, res.new_canonical)
            st, canon = jax.lax.fori_loop(0, n_chunks, body,
                                          (store, canonical))
            return st.hi, canon
    elif name == "stream":
        @jax.jit
        def run(store, cs):
            st, res = pallas_fanin_stream(store, cs, canonical,
                                          jnp.int32(0), wall,
                                          n_chunks=n_chunks)
            return st.hi, res.new_canonical
    elif name == "stream-noguard":
        canon_hi, canon_lo = _split64(canonical)
        scalars = jnp.stack([canon_hi, canon_lo.astype(jnp.int32),
                             jnp.int32(0), canon_hi,
                             canon_lo.astype(jnp.int32), canon_hi,
                             canon_lo.astype(jnp.int32)]).astype(jnp.int32)

        @jax.jit
        def run(store, cs):
            hi = _stream_call(_stream_noguard_kernel, store, cs, scalars,
                              n_chunks)
            return hi, hi[0]
    else:
        kernel = _join_only_kernel if name == "nojoin" else _copy_kernel
        canon_hi, canon_lo = _split64(canonical)
        scalars = jnp.stack([canon_hi, canon_lo.astype(jnp.int32),
                             jnp.int32(0), canon_hi,
                             canon_lo.astype(jnp.int32), canon_hi,
                             canon_lo.astype(jnp.int32)]).astype(jnp.int32)

        @jax.jit
        def run(store, cs):
            def body(i, st):
                hi = _variant_call(kernel, st, cs, scalars)
                return st._replace(hi=hi)
            st = jax.lax.fori_loop(0, n_chunks, body, store)
            return st.hi, st.hi[0]

    out, tok = run(store, cs)
    jax.device_get(tok)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out, tok = run(store, cs)
        jax.device_get(tok)
        best = min(best, time.perf_counter() - t0)
    merges = int(jnp.sum(cs.hi != cs.hi.min())) * n_chunks
    gbytes = ((6 * chunk + 2 * 9) * n_keys * 4) * n_chunks / 1e9
    print(f"{name:8s} {best * 1e3:8.1f} ms   {merges / best / 1e9:6.2f} "
          f"B merges/s   {gbytes / best:6.1f} GB/s effective")
    return best


def _copy_batch_kernel(narrow_val, scalars_ref, *refs):
    """Pure-copy at the EXACT production batch geometry (VERDICT r4
    item 4): same narrow wire lanes, same (8, 512) tile, same
    (row_block, chunk) grid and index maps as `pallas_fanin_batch` —
    chunk c reads row group c while the store block stays resident
    across c. One add per lane defeats DCE; no compares, no selects.
    What this measures IS the memory system's ceiling for the
    distinct-batch layout."""
    if narrow_val:
        (cs_hi, cs_lo, cs_node, cs_v32, cs_tomb,
         st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
         st_mhi, st_mlo, st_mnode,
         o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
         o_mhi, o_mlo, o_mnode, win_ref) = refs
    else:
        (cs_hi, cs_lo, cs_node, cs_vhi, cs_vlo, cs_tomb,
         st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
         st_mhi, st_mlo, st_mnode,
         o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
         o_mhi, o_mlo, o_mnode, win_ref) = refs
    c = pl.program_id(1)
    first = c == 0
    a_hi = cs_hi[0]
    a_lo = cs_lo[0]
    a_node = cs_node[0]
    # i8 vector adds don't lower on Mosaic; widen on load like the
    # production kernel (the VMEM read is still 1 B/lane)
    a_tomb = cs_tomb[0].astype(jnp.int32)
    if narrow_val:
        a_v = cs_v32[0]
    else:
        a_vhi = cs_vhi[0]
        a_vlo = cs_vlo[0]
    for r in range(1, cs_hi.shape[0]):
        a_hi = a_hi + cs_hi[r]
        a_lo = a_lo + cs_lo[r]
        a_node = a_node + cs_node[r]
        a_tomb = a_tomb + cs_tomb[r].astype(jnp.int32)
        if narrow_val:
            a_v = a_v + cs_v32[r]
        else:
            a_vhi = a_vhi + cs_vhi[r]
            a_vlo = a_vlo + cs_vlo[r]
    o_hi[...] = jnp.where(first, st_hi[...], o_hi[...]) + a_hi
    o_lo[...] = jnp.where(first, st_lo[...], o_lo[...]) + a_lo
    o_node[...] = (jnp.where(first, st_node[...], o_node[...])
                   + a_node.astype(jnp.int32))
    if narrow_val:
        o_vhi[...] = jnp.where(first, st_vhi[...], o_vhi[...]) + (a_v >> 31)
        o_vlo[...] = (jnp.where(first, st_vlo[...], o_vlo[...])
                      + a_v.astype(jnp.uint32))
    else:
        o_vhi[...] = jnp.where(first, st_vhi[...], o_vhi[...]) + a_vhi
        o_vlo[...] = jnp.where(first, st_vlo[...], o_vlo[...]) + a_vlo
    o_tomb[...] = jnp.where(first, st_tomb[...], o_tomb[...]) + a_tomb
    o_mhi[...] = jnp.where(first, st_mhi[...], o_mhi[...])
    o_mlo[...] = jnp.where(first, st_mlo[...], o_mlo[...])
    o_mnode[...] = jnp.where(first, st_mnode[...], o_mnode[...])
    win_ref[...] = a_node.astype(jnp.int32)


def run_batch_copy(n_keys: int, n_rows: int, chunk_rows: int = 16,
                   loops: int = 48, value_width: int = 64,
                   repeats: int = 3) -> float:
    """`bench_distinct`'s protocol with `pallas_fanin_batch` swapped
    for the same-layout pure-copy kernel: identical narrow lanes,
    tiles, grid, index maps, store aliasing, loop chaining, and fence.
    The merges/s this prints is the HBM ceiling the production
    distinct row can be compared against directly."""
    from functools import partial
    from crdt_tpu.ops.pallas_merge import split_changeset_narrow
    store = split_store(empty_dense_store(n_keys))
    cs = make_changeset(n_rows, n_keys, seed=0)
    merges = int(jnp.sum(cs.valid))
    if value_width == 32:
        scs, _ = split_changeset_narrow(cs._replace(val=cs.val & 0x7FFFFFFF))
    else:
        scs = split_changeset(cs)
    jax.block_until_ready(scs)
    del cs
    n_cs = len(scs)
    r, n = scs.hi.shape
    rows = n // _LANE
    n_chunks = r // chunk_rows
    _i32 = jnp.int32
    scalars = jnp.zeros((7,), jnp.int32)
    cs_spec = pl.BlockSpec((chunk_rows, _SB, _LANE),
                           lambda i, c: (c, _i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((_SB, _LANE), lambda i, c: (_i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    cs3d = [lane.reshape(r, rows, _LANE) for lane in scs]
    st_dtypes = [lane.dtype for lane in store]
    out_shapes = ([jax.ShapeDtypeStruct((rows, _LANE), d)
                   for d in st_dtypes] +
                  [jax.ShapeDtypeStruct((rows, _LANE), jnp.int32)])

    call = pl.pallas_call(
        partial(_copy_batch_kernel, n_cs == 5),
        grid=(rows // _SB, n_chunks),
        in_specs=([pl.BlockSpec((7,), lambda i, c: (_i32(0),),
                                memory_space=pltpu.SMEM)] +
                  [cs_spec] * n_cs + [st_spec] * 9),
        out_specs=tuple([st_spec] * 10),
        out_shape=tuple(out_shapes),
        input_output_aliases={1 + n_cs + j: j for j in range(9)},
    )

    @jax.jit
    def run(st2d, cs3d):
        outs = call(scalars, *cs3d, *st2d)
        return list(outs[:9]), outs[0][0, 0]

    st2d = [lane.reshape(rows, _LANE) for lane in store]
    st2d, tok = run(st2d, cs3d)
    jax.device_get(tok)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(loops):
            st2d, tok = run(st2d, cs3d)
        jax.device_get(tok)
        best = min(best, time.perf_counter() - t0)
    cs_bytes = sum(ln.dtype.itemsize for ln in scs) * r * n
    gbytes = cs_bytes * loops / 1e9   # store blocks amortize over chunks
    name = f"copy-batch{'-valref' if n_cs == 5 else ''}"
    print(f"{name:18s} {best * 1e3:8.1f} ms   "
          f"{merges * loops / best / 1e9:6.2f} B merges/s   "
          f"{gbytes / best:6.1f} GB/s cs-lane traffic")
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1 << 20)
    ap.add_argument("--replicas", type=int, default=1024)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--variants", default="full,nojoin,copy")
    ap.add_argument("--rows", type=int, default=128,
                    help="copy-batch: HBM-resident distinct rows")
    ap.add_argument("--loops", type=int, default=48)
    args = ap.parse_args()
    for name in args.variants.split(","):
        if name == "copy-batch":
            run_batch_copy(args.keys, args.rows, loops=args.loops)
        elif name == "copy-batch-valref":
            run_batch_copy(args.keys, args.rows, loops=args.loops,
                           value_width=32)
        else:
            run_variant(name, args.keys, args.replicas, args.chunk)


if __name__ == "__main__":
    main()

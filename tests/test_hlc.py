"""HLC clock unit tests — port of the reference `test/hlc_test.dart`.

Golden constants are language-neutral and pinned at hlc_test.dart:4-7:
millis 1000000000000, ISO '2001-09-09T01:46:40.000Z',
logicalTime 65536000000000066, packed '00cre66i9s001uabc'.
"""

import pytest

from crdt_tpu import (ClockDriftException, DuplicateNodeException, Hlc,
                      OverflowException)

MILLIS = 1000000000000
ISO_TIME = "2001-09-09T01:46:40.000Z"
LOGICAL_TIME = 65536000000000066
PACKED = "00cre66i9s001uabc"


class TestConstructors:
    hlc = Hlc(MILLIS, 0x42, "abc")

    def test_default(self):
        assert self.hlc.millis == MILLIS
        assert self.hlc.counter == 0x42
        assert self.hlc.node_id == "abc"

    def test_default_with_microseconds(self):
        assert Hlc(MILLIS * 1000, 0x42, "abc") == self.hlc

    def test_default_with_copy_with(self):
        assert self.hlc.copy_with(node_id="xyz").node_id == "xyz"

    def test_zero(self):
        assert Hlc.zero("abc") == self.hlc.apply(millis=0, counter=0)

    def test_from_date(self):
        from datetime import datetime, timezone
        dt = datetime(2001, 9, 9, 1, 46, 40, tzinfo=timezone.utc)
        assert Hlc.from_date(dt, "abc") == self.hlc.apply(counter=0)

    def test_logical_time_ctor(self):
        assert Hlc.from_logical_time(LOGICAL_TIME, "abc") == self.hlc

    def test_parse(self):
        assert Hlc.parse(f"{ISO_TIME}-0042-abc") == self.hlc


class TestStringOperations:
    def test_hlc_to_string(self):
        hlc = Hlc.parse(f"{ISO_TIME}-0042-abc")
        assert str(hlc) == f"{ISO_TIME}-0042-abc"

    def test_parse_hlc(self):
        assert Hlc.parse(f"{ISO_TIME}-0042-abc") == Hlc(MILLIS, 0x42, "abc")


class TestNonStringNodeId:
    def test_to_hlc(self):
        hlc = Hlc.parse(f"{ISO_TIME}-0042-1", int)
        assert hlc == Hlc(MILLIS, 0x42, 1)

    def test_to_string(self):
        hlc = Hlc(MILLIS, 0x42, 1)
        assert str(hlc) == f"{ISO_TIME}-0042-1"


class TestComparison:
    def test_equality(self):
        hlc1 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        hlc2 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        assert hlc1 == hlc2
        assert hlc1 <= hlc2
        assert hlc1 >= hlc2

    def test_different_node_ids(self):
        hlc1 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        hlc2 = Hlc.parse(f"{ISO_TIME}-0042-abcd")
        assert hlc1 != hlc2

    def test_less_than_millis(self):
        assert Hlc(MILLIS, 0x42, "abc") < Hlc(MILLIS + 1, 0, "abc")
        assert Hlc(MILLIS, 0x42, "abc") <= Hlc(MILLIS + 1, 0, "abc")

    def test_less_than_counter(self):
        hlc1 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        hlc2 = Hlc.parse(f"{ISO_TIME}-0043-abc")
        assert hlc1 < hlc2
        assert hlc1 <= hlc2

    def test_less_than_node_id(self):
        hlc1 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        hlc2 = Hlc.parse(f"{ISO_TIME}-0042-abb")
        assert hlc1 > hlc2
        assert hlc1 >= hlc2

    def test_fail_less_than_if_equals(self):
        hlc1 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        hlc2 = Hlc.parse(f"{ISO_TIME}-0042-abc")
        assert not (hlc1 < hlc2)

    def test_fail_less_than_if_millis_and_counter_disagree(self):
        assert not (Hlc(MILLIS + 1, 0, "abc") < Hlc(MILLIS, 0x42, "abc"))

    def test_more_than_millis(self):
        assert Hlc(MILLIS + 1, 0x42, "abc") > Hlc(MILLIS, 0, "abc")
        assert Hlc(MILLIS + 1, 0x42, "abc") >= Hlc(MILLIS, 0, "abc")

    def test_more_than_node_id(self):
        assert Hlc(MILLIS, 0x42, "abc") > Hlc(MILLIS, 0x42, "abb")
        assert Hlc(MILLIS, 0x42, "abc") >= Hlc(MILLIS, 0x42, "abb")

    def test_compare(self):
        hlc = Hlc(MILLIS, 0x42, "abc")
        assert hlc.compare_to(Hlc(MILLIS, 0x42, "abc")) == 0

        assert hlc.compare_to(Hlc(MILLIS + 1, 0x42, "abc")) == -1
        assert hlc.compare_to(Hlc(MILLIS, 0x43, "abc")) == -1
        assert hlc.compare_to(Hlc(MILLIS, 0x42, "abd")) == -1

        assert hlc.compare_to(Hlc(MILLIS - 1, 0x42, "abc")) == 1
        assert hlc.compare_to(Hlc(MILLIS, 0x41, "abc")) == 1
        assert hlc.compare_to(Hlc(MILLIS, 0x42, "abb")) == 1


class TestLogicalTime:
    def test_stability(self):
        hlc = Hlc.from_logical_time(LOGICAL_TIME, "abc")
        assert hlc.logical_time == LOGICAL_TIME

    def test_hlc_as_logical_time(self):
        assert Hlc.parse(f"{ISO_TIME}-0042-abc").logical_time == LOGICAL_TIME

    def test_hlc_from_logical_time(self):
        hlc = Hlc.parse(f"{ISO_TIME}-0042-abc")
        assert Hlc.from_logical_time(LOGICAL_TIME, "abc") == hlc


class TestPacking:
    def test_pack(self):
        assert Hlc(MILLIS, 0x42, "abc").pack() == PACKED

    def test_unpack(self):
        hlc = Hlc.unpack(PACKED)
        assert hlc.millis == MILLIS
        assert hlc.counter == 0x42
        assert hlc.node_id == "abc"

    def test_random_node_id(self):
        nid = Hlc.random_node_id()
        assert len(nid) == 10
        assert all(c in "0123456789abcdefghijklmnopqrstuvwxyz" for c in nid)


class TestSend:
    def test_higher_canonical_time(self):
        hlc = Hlc(MILLIS + 1, 0x42, "abc")
        send_hlc = Hlc.send(hlc, millis=MILLIS)
        assert send_hlc != hlc
        assert send_hlc.millis == hlc.millis
        assert send_hlc.counter == 0x43
        assert send_hlc.node_id == hlc.node_id

    def test_equal_canonical_time(self):
        hlc = Hlc(MILLIS, 0x42, "abc")
        send_hlc = Hlc.send(hlc, millis=MILLIS)
        assert send_hlc != hlc
        assert send_hlc.millis == MILLIS
        assert send_hlc.counter == 0x43

    def test_lower_canonical_time(self):
        hlc = Hlc(MILLIS - 1, 0x42, "abc")
        send_hlc = Hlc.send(hlc, millis=MILLIS)
        assert send_hlc != hlc
        assert send_hlc.millis == MILLIS
        assert send_hlc.counter == 0

    def test_fail_on_clock_drift(self):
        hlc = Hlc(MILLIS + 60001, 0, "abc")
        with pytest.raises(ClockDriftException):
            Hlc.send(hlc, millis=MILLIS)

    def test_fail_on_counter_overflow(self):
        hlc = Hlc(MILLIS, 0xFFFF, "abc")
        with pytest.raises(OverflowException):
            Hlc.send(hlc, millis=MILLIS)


class TestReceive:
    canonical = Hlc.parse(f"{ISO_TIME}-0042-abc")

    def test_higher_canonical_time(self):
        remote = Hlc(MILLIS - 1, 0x42, "abcd")
        assert Hlc.recv(self.canonical, remote, millis=MILLIS) == \
            self.canonical

    def test_same_remote_time(self):
        remote = Hlc(MILLIS, 0x42, "abcd")
        hlc = Hlc.recv(self.canonical, remote, millis=MILLIS)
        assert hlc == Hlc(remote.millis, remote.counter,
                          self.canonical.node_id)

    def test_higher_remote_time(self):
        remote = Hlc(MILLIS + 1, 0, "abcd")
        hlc = Hlc.recv(self.canonical, remote, millis=MILLIS)
        assert hlc == Hlc(remote.millis, remote.counter,
                          self.canonical.node_id)

    def test_higher_wall_clock_time(self):
        remote = Hlc.parse(f"{ISO_TIME}-0000-abcd")
        assert Hlc.recv(self.canonical, remote, millis=MILLIS + 1) == \
            self.canonical

    def test_skip_node_id_check_if_time_is_lower(self):
        remote = Hlc(MILLIS - 1, 0x42, "abc")
        assert Hlc.recv(self.canonical, remote, millis=MILLIS) == \
            self.canonical

    def test_skip_node_id_check_if_time_is_same(self):
        remote = Hlc(MILLIS, 0x42, "abc")
        assert Hlc.recv(self.canonical, remote, millis=MILLIS) == \
            self.canonical

    def test_fail_on_node_id(self):
        remote = Hlc(MILLIS + 1, 0, "abc")
        with pytest.raises(DuplicateNodeException):
            Hlc.recv(self.canonical, remote, millis=MILLIS)

    def test_fail_on_clock_drift(self):
        remote = Hlc(MILLIS + 60001, 0x42, "abcd")
        with pytest.raises(ClockDriftException):
            Hlc.recv(self.canonical, remote, millis=MILLIS)

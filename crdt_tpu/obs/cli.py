"""``python -m crdt_tpu.obs`` — poll a live node or summarize a trace.

Two modes:

- **Poll** a running `SyncServer` / `GossipNode` via the ``metrics``
  wire op and render the snapshot (human summary by default, raw
  JSON with ``--json``, Prometheus text with ``--prom``)::

      python -m crdt_tpu.obs --once 127.0.0.1:7000
      python -m crdt_tpu.obs 127.0.0.1:7000 --interval 5   # loop

- **Summarize** a trace JSONL (written by
  ``tracer().enable(jsonl_path=...)``) into a per-phase latency
  table::

      python -m crdt_tpu.obs --trace /tmp/crdt-trace.jsonl

The ``fleet`` subcommand scrapes N replicas into a canary lag matrix
and SLO verdict (see :mod:`crdt_tpu.obs.fleet`)::

    python -m crdt_tpu.obs fleet --peers a=127.0.0.1:7000,b=127.0.0.1:7001 --once

The ``bench`` subcommand verdicts the newest bench-trajectory record
against the fastest-of-N floors of its group — the CI regression gate
(see :mod:`crdt_tpu.obs.trajectory`)::

    python -m crdt_tpu.obs bench --compare benchmarks/history/trajectory.jsonl

The ``dump`` subcommand fetches a node's SLO flight-recorder bundles
over the ``debug_dump`` wire op — post-incident forensics without a
poller having been attached (see :mod:`crdt_tpu.obs.recorder`)::

    python -m crdt_tpu.obs dump 127.0.0.1:7000
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from .render import (format_phase_table, render_prometheus,
                     render_summary, summarize_trace)


def _parse_target(target: str):
    host, sep, port = target.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(f"target must be host:port, got {target!r}")
    return host or "127.0.0.1", int(port)


def _render(snapshot: dict, mode: str) -> str:
    if mode == "json":
        return json.dumps(snapshot, indent=2, default=str) + "\n"
    if mode == "prom":
        return render_prometheus(snapshot)
    return render_summary(snapshot)


def _summarize_file(path: str, out) -> int:
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue   # half-written tail line of a live sink
    out.write(format_phase_table(summarize_trace(events)))
    return 0


def _format_bundle(bundle: dict) -> str:
    """One flight-recorder bundle as a compact human block."""
    lines = [f"bundle #{bundle.get('seq', '?')} "
             f"kind={bundle.get('kind')} "
             f"t_wall_ms={bundle.get('t_wall_ms')}"]
    ctx = bundle.get("context")
    if ctx:
        lines.append(f"  context: {json.dumps(ctx, default=str)}")
    trace = bundle.get("trace")
    if isinstance(trace, list):
        lines.append(f"  trace tail: {len(trace)} events")
        lines.append(format_phase_table(summarize_trace(trace))
                     .rstrip().replace("\n", "\n  "))
    sketches = bundle.get("sketches")
    if isinstance(sketches, dict) and sketches:
        from .sketch import sketch_from_sample
        for name, samples in sorted(sketches.items()):
            for s in samples:
                sk = sketch_from_sample(s)
                if sk is None or sk.count == 0:
                    continue
                lines.append(
                    f"  {name}{s.get('labels', {})}: "
                    f"count={sk.count} "
                    f"p50={sk.quantile(0.5):.6f} "
                    f"p99={sk.quantile(0.99):.6f}")
    for src in bundle.get("sources", []):
        if isinstance(src, dict):
            keys = ", ".join(sorted(src))
            lines.append(f"  source sections: {keys}")
    return "\n".join(lines) + "\n"


def _dump_main(argv: List[str], out) -> int:
    """``python -m crdt_tpu.obs dump`` — fetch a node's flight-
    recorder bundles (obs/recorder.py) over the ``debug_dump`` op."""
    ap = argparse.ArgumentParser(
        prog="python -m crdt_tpu.obs dump",
        description="fetch a node's SLO flight-recorder debug "
                    "bundles (post-incident forensics)")
    ap.add_argument("target",
                    help="host:port of a running SyncServer/ServeTier")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--json", action="store_true",
                    help="print raw bundle JSON (one per line)")
    args = ap.parse_args(argv)
    host, port = _parse_target(args.target)
    from ..net import SyncError, fetch_debug_dump
    try:
        bundles = fetch_debug_dump(host, port, timeout=args.timeout)
    except SyncError as e:
        print(f"dump failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        for b in bundles:
            out.write(json.dumps(b, default=str) + "\n")
    elif not bundles:
        out.write("no bundles recorded\n")
    else:
        for b in bundles:
            out.write(_format_bundle(b))
    return 0


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "fleet":
        from .fleet import fleet_main
        return fleet_main(argv[1:], out)
    if argv and argv[0] == "bench":
        from .trajectory import bench_main
        return bench_main(argv[1:], out)
    if argv and argv[0] == "dump":
        return _dump_main(argv[1:], out)
    ap = argparse.ArgumentParser(
        prog="python -m crdt_tpu.obs",
        description="poll a node's metrics op, or summarize a trace "
                    "JSONL into a per-phase latency table")
    ap.add_argument("target", nargs="?",
                    help="host:port of a running SyncServer/GossipNode")
    ap.add_argument("--once", action="store_true",
                    help="poll once and exit (default: loop)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll period in seconds (loop mode)")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="per-poll socket timeout")
    ap.add_argument("--json", action="store_true",
                    help="print the raw snapshot JSON")
    ap.add_argument("--prom", action="store_true",
                    help="print Prometheus text exposition")
    ap.add_argument("--trace", metavar="JSONL",
                    help="summarize a trace JSONL instead of polling")
    args = ap.parse_args(argv)

    if args.trace:
        return _summarize_file(args.trace, out)
    if not args.target:
        ap.error("need a host:port target (or --trace JSONL)")
    mode = "json" if args.json else "prom" if args.prom else "summary"
    host, port = _parse_target(args.target)

    # Imported lazily: obs must stay importable below net (net's
    # server attaches its wire tally to this package's registry).
    from ..net import SyncError, fetch_metrics

    while True:
        try:
            snapshot = fetch_metrics(host, port,
                                     timeout=args.timeout)
        except SyncError as e:
            print(f"poll failed: {e}", file=sys.stderr)
            return 1
        out.write(_render(snapshot, mode))
        if args.once:
            return 0
        out.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
